"""graftward degradation-response units (dalle_tpu/degrade/): the
straggler detector's wait-inversion math and hysteresis, the response
ladder's one-action-per-edge semantics, the wedge watchdog's arm gate and
no-false-positive behavior, and the elastic heartbeat page plumbing.

Everything here is pure host-side python over injected clocks/heartbeat
dicts — the real two-plane integrations run in scripts/chaos_smoke.py
(straggler_reshape) and scripts/fleet_smoke.py (wedge_drain).
"""

import json
import os

import pytest

from dalle_tpu.degrade import (DegradeMonitor, StragglerDetector,
                               WedgeWatchdog, frozen_progress,
                               install_breach_pager)
from dalle_tpu.parallel import elastic

# ---------------------------------------------------------------------------
# heartbeat-stream builders: lockstep fleet where every worker completes
# step s at the same wall time (the coupled interval), but each carries its
# own self-measured blocked_s (the wait-inversion signal)
# ---------------------------------------------------------------------------


def beats_at(step, t, blocked):
    """blocked: {wid: blocked_s}; arrival identical across the fleet —
    the lockstep reality the detector must see through."""
    return {w: {"step": step, "step_time": t, "blocked_s": b}
            for w, b in blocked.items()}


def drive(det_or_mon, rounds, members=(0, 1)):
    """Feed a list of (step, t, {wid: blocked}) rounds; returns all
    emitted verdicts/actions."""
    out = []
    for step, t, blocked in rounds:
        out.extend(det_or_mon.observe(beats_at(step, t, blocked),
                                      list(members)))
    return out


def lockstep(n_steps, interval, victim_blocked, peer_blocked,
             victim=1, peers=(0,), start_step=1):
    rounds = []
    t = 0.0
    for i in range(n_steps):
        t += interval
        blocked = {w: peer_blocked for w in peers}
        blocked[victim] = victim_blocked
        rounds.append((start_step + i, t, blocked))
    return rounds


# ---------------------------------------------------------------------------
# StragglerDetector
# ---------------------------------------------------------------------------

def test_detector_warmup_emits_nothing():
    det = StragglerDetector(factor=0.4, sustain=1, warmup_steps=4)
    # a blatant straggler, but only warmup_steps rounds: EWMAs have no
    # baseline yet — no verdict may fire
    verdicts = drive(det, lockstep(4, 1.0, victim_blocked=0.02,
                                   peer_blocked=0.9))
    assert verdicts == []


def test_detector_flags_victim_not_peer_n2():
    """The n=2 median-robustness case: the reference is the median of the
    OTHER workers (= the peer), so the victim carries the full inversion
    and the peer's deficit is negative — a whole-fleet median would split
    it and flag nobody."""
    det = StragglerDetector(factor=0.4, sustain=2, warmup_steps=2)
    verdicts = drive(det, lockstep(8, 1.0, victim_blocked=0.03,
                                   peer_blocked=0.85))
    assert [v.worker_id for v in verdicts] == [1]
    v = verdicts[0]
    assert v.deficit_s == pytest.approx(0.82, abs=0.05)
    assert v.ratio > 0.4
    assert det.is_flagged(1) and not det.is_flagged(0)
    assert det.deficit_of(0) < 0            # the peer WAITS — never flagged


def test_detector_healthy_fleet_quiet():
    det = StragglerDetector(factor=0.4, sustain=2, warmup_steps=2)
    rounds = []
    t = 0.0
    for s in range(1, 30):
        t += 0.1
        # ±2ms jitter in who waits a hair longer
        rounds.append((s, t, {0: 0.08 + 0.002 * (s % 2),
                              1: 0.08 + 0.002 * ((s + 1) % 2)}))
    assert drive(det, rounds) == []


def test_detector_single_spike_never_trips_sustain():
    det = StragglerDetector(factor=0.4, sustain=3, warmup_steps=2,
                            alpha=1.0)   # no smoothing: isolate sustain
    rounds = lockstep(4, 0.5, victim_blocked=0.4, peer_blocked=0.4)
    # one spiked step (a GC pause / checkpoint boundary on worker 1)
    rounds += lockstep(1, 1.0, victim_blocked=0.02, peer_blocked=0.9,
                       start_step=5)
    rounds += lockstep(4, 0.5, victim_blocked=0.4, peer_blocked=0.4,
                       start_step=6)
    assert drive(det, rounds) == []


def test_detector_edge_trigger_and_hysteresis_recovery():
    det = StragglerDetector(factor=0.4, sustain=2, warmup_steps=2,
                            recover_ratio=0.5, alpha=1.0)
    rounds = lockstep(8, 1.0, victim_blocked=0.02, peer_blocked=0.9)
    verdicts = drive(det, rounds)
    assert len(verdicts) == 1               # ONE edge, not one per step
    # recovery must cross BELOW recover_ratio × threshold to clear:
    # a deficit in the hysteresis band holds the flagged state
    thresh = det.factor * det.interval_ewma
    in_band = thresh * 0.7                  # above recover (0.5×), below trip
    drive(det, lockstep(3, 1.0, victim_blocked=0.9 - in_band,
                        peer_blocked=0.9, start_step=9))
    assert det.is_flagged(1)
    drive(det, lockstep(3, 1.0, victim_blocked=0.9, peer_blocked=0.9,
                        start_step=12))
    assert not det.is_flagged(1)            # clean recovery clears
    # a relapse re-arms the edge: a second verdict may fire
    verdicts2 = drive(det, lockstep(4, 1.0, victim_blocked=0.02,
                                    peer_blocked=0.9, start_step=15))
    assert [v.worker_id for v in verdicts2] == [1]


def test_detector_inert_without_blocked_signal_and_small_fleets():
    det = StragglerDetector(sustain=1, warmup_steps=1)
    # old heartbeats (no blocked_s) make it inert, not wrong
    rounds = [(s, float(s), {0: None, 1: None}) for s in range(1, 8)]
    assert drive(det, rounds) == []
    # one-member fleets have nobody to wait for
    det2 = StragglerDetector(sustain=1, warmup_steps=1)
    assert det2.observe({0: {"step": 3, "step_time": 1.0,
                             "blocked_s": 0.0}}, [0]) == []


def test_detector_reset_clears_verdict_state():
    det = StragglerDetector(factor=0.4, sustain=2, warmup_steps=2)
    drive(det, lockstep(8, 1.0, victim_blocked=0.02, peer_blocked=0.9))
    assert det.is_flagged(1)
    det.reset()
    assert not det.is_flagged(1) and det.processed == 0
    # post-reset: warmup applies again before anything can fire
    assert drive(det, lockstep(2, 1.0, victim_blocked=0.02,
                               peer_blocked=0.9)) == []


def test_frozen_progress_core():
    # the shared fresh-but-frozen predicate (elastic.hung_workers + the
    # fleet transport's outside-in wedge check ride this)
    assert frozen_progress(5, 100.0, now=103.0, timeout_s=2.0)
    assert not frozen_progress(5, 100.0, now=101.0, timeout_s=2.0)
    assert not frozen_progress(None, None, now=1e9, timeout_s=2.0)  # arm gate


# ---------------------------------------------------------------------------
# DegradeMonitor: the page → drain ladder
# ---------------------------------------------------------------------------

def _mon(escalate=2, **det_kw):
    det_kw.setdefault("factor", 0.4)
    det_kw.setdefault("sustain", 2)
    det_kw.setdefault("warmup_steps", 2)
    return DegradeMonitor(StragglerDetector(**det_kw),
                          straggler_escalate=escalate)


def test_ladder_pages_then_escalates_once_each():
    mon = _mon(escalate=2)
    actions = drive(mon, lockstep(12, 1.0, victim_blocked=0.02,
                                  peer_blocked=0.9))
    kinds = [(a.kind, a.worker_id, a.reason) for a in actions]
    assert kinds == [("page", 1, "straggler"), ("drain", 1, "straggler")]
    # the drain rung fires AFTER the page rung, not with it
    page_i = kinds.index(("page", 1, "straggler"))
    drain_i = kinds.index(("drain", 1, "straggler"))
    assert drain_i > page_i
    # continued degradation after the drain: NO further actions (the
    # agent reshapes; this monitor's job for worker 1 is done)
    assert drive(mon, lockstep(6, 1.0, victim_blocked=0.02,
                               peer_blocked=0.9, start_step=13)) == []


def test_ladder_recovery_between_rungs_resets_to_ok():
    # alpha=1 so the recovery clears the EWMA within the escalation
    # window — the smoothed default would (correctly) still drain a
    # victim whose deficit is only just starting to decay
    mon = _mon(escalate=4, alpha=1.0)
    actions = drive(mon, lockstep(5, 1.0, victim_blocked=0.02,
                                  peer_blocked=0.9))
    assert [a.kind for a in actions] == ["page"]
    # full recovery before the escalation window elapses → no drain
    actions2 = drive(mon, lockstep(8, 0.5, victim_blocked=0.4,
                                   peer_blocked=0.4, start_step=6))
    assert actions2 == []
    assert not mon.detector.is_flagged(1)


def test_ladder_health_page_goes_straight_to_drain_once():
    mon = _mon()
    beats = beats_at(3, 1.0, {0: 0.1, 1: 0.1})
    beats[1]["page"] = "nan-precursor:transformer"
    actions = mon.observe(beats, [0, 1])
    assert [(a.kind, a.worker_id, a.reason) for a in actions] == [
        ("page", 1, "health_page"), ("drain", 1, "health_page")]
    assert "nan-precursor" in actions[1].detail
    # sticky marker in later beats: edge already consumed, no re-fire
    beats2 = beats_at(4, 2.0, {0: 0.1, 1: 0.1})
    beats2[1]["page"] = "nan-precursor:transformer"
    assert mon.observe(beats2, [0, 1]) == []


def test_ladder_reset_forgets_pages_and_rungs():
    mon = _mon()
    beats = beats_at(3, 1.0, {0: 0.1, 1: 0.1})
    beats[1]["page"] = "grad-explosion:decoder"
    assert len(mon.observe(beats, [0, 1])) == 2
    mon.reset()
    # the NEXT epoch's fresh page is a fresh edge (quarantine-respawn that
    # pages again must drain again — max_reconfigures bounds the loop)
    assert len(mon.observe(beats, [0, 1])) == 2


# ---------------------------------------------------------------------------
# WedgeWatchdog
# ---------------------------------------------------------------------------

class _Probe:
    def __init__(self):
        self.progress = 0
        self.busy = False

    def __call__(self):
        return self.progress, self.busy


def _wd(probe, timeout=1.0, trips=None):
    return WedgeWatchdog(probe, timeout,
                         on_wedge=(trips.append if trips is not None
                                   else None))


def test_watchdog_arm_gate_ignores_first_compile():
    """A cold engine paying its first trace+compile inside the first
    dispatch is busy with a frozen counter for a LONG time — slow, not
    wedged. No trip until progress has advanced at least once."""
    p, trips = _Probe(), []
    wd = _wd(p, timeout=1.0, trips=trips)
    p.busy = True                           # request admitted, compiling
    for t in range(0, 300, 10):
        assert wd.check(now=float(t)) is False
    assert trips == [] and not wd.wedged


def test_watchdog_idle_is_healthy_forever():
    p, trips = _Probe(), []
    wd = _wd(p, timeout=1.0, trips=trips)
    p.progress, p.busy = 5, False
    wd.check(now=0.0)
    wd.check(now=1.0)                       # arm (progress seen to move)
    p.progress = 6
    wd.check(now=2.0)
    for t in range(3, 1000, 50):
        assert wd.check(now=float(t)) is False
    assert trips == []


def test_watchdog_no_false_positive_during_long_prefill():
    """A legitimate long prefill is ONE bounded dispatch: the counter
    freezes for under the timeout, then bumps. As long as every dispatch
    beats the timeout, the watchdog stays quiet — the timeout's contract
    is 'longer than the longest legitimate single dispatch'."""
    p, trips = _Probe(), []
    wd = _wd(p, timeout=1.0, trips=trips)
    p.busy = True
    t = 0.0
    p.progress = 1
    wd.check(now=t)
    p.progress = 2
    wd.check(now=t + 0.1)                   # armed
    for _ in range(20):                     # long prefills: 0.9s each
        t += 0.9
        p.progress += 1
        assert wd.check(now=t) is False
    assert trips == [] and not wd.wedged


def test_watchdog_arms_from_counter_value_alone():
    """A request can race the engine from idle to wedged inside ONE poll
    interval: the watchdog's first observation is already the frozen
    value. The counter being > 0 is itself the arm evidence — requiring a
    change between two polls would never arm (the fleet_smoke wedge_drain
    regression)."""
    p, trips = _Probe(), []
    wd = _wd(p, timeout=1.0, trips=trips)
    p.progress, p.busy = 11, True           # first look: already wedged
    assert wd.check(now=0.0) is False
    assert wd.check(now=1.5) is True
    assert wd.wedged and len(trips) == 1


def test_watchdog_trips_once_per_episode_and_rearms():
    p, trips = _Probe(), []
    wd = _wd(p, timeout=1.0, trips=trips)
    p.busy = True
    p.progress = 1
    wd.check(now=0.0)
    p.progress = 2
    wd.check(now=0.1)                       # armed
    # frozen + busy past the timeout: exactly ONE trip, then latched
    assert wd.check(now=0.5) is False
    assert wd.check(now=1.5) is True
    assert wd.check(now=2.5) is False       # edge, not a page storm
    assert wd.wedged and len(trips) == 1
    assert "no iteration progress" in trips[0]
    # progress resumes → re-arms; a second wedge is a second edge
    p.progress = 3
    wd.check(now=3.0)
    assert not wd.wedged
    assert wd.check(now=4.5) is True
    assert len(trips) == 2


def test_watchdog_survives_probe_and_sink_failures():
    calls = []

    def bad_probe():
        calls.append(1)
        raise RuntimeError("engine is gone")

    wd = WedgeWatchdog(bad_probe, 1.0, log=lambda *_: None)
    assert wd.check(now=0.0) is False       # logged, not raised
    p = _Probe()

    def bad_sink(detail):
        raise RuntimeError("pager down")

    wd2 = WedgeWatchdog(p, 1.0, on_wedge=bad_sink, log=lambda *_: None)
    p.busy = True
    p.progress = 1
    wd2.check(now=0.0)
    p.progress = 2
    wd2.check(now=0.1)
    assert wd2.check(now=2.0) is True       # wedge latched despite the sink
    assert wd2.wedged


# ---------------------------------------------------------------------------
# heartbeat page plumbing (parallel/elastic.py) + the sentry pager
# ---------------------------------------------------------------------------

def test_heartbeat_page_is_published_and_sticky(tmp_path):
    d = str(tmp_path)
    hb = elastic.Heartbeat(d, 0, interval_s=30.0)
    hb.beat(step=3, epoch=0, force=True)
    assert elastic.read_heartbeats(d)[0].get("page") is None
    hb.page("nan-precursor:encoder", epoch=0)
    assert elastic.read_heartbeats(d)[0]["page"] == "nan-precursor:encoder"
    # sticky: later beats re-publish the marker (a page lost to an agent
    # restart is re-learned from any subsequent beat)
    hb.beat(step=4, force=True)
    assert elastic.read_heartbeats(d)[0]["page"] == "nan-precursor:encoder"


def test_heartbeat_carries_blocked_s(tmp_path):
    d = str(tmp_path)
    hb = elastic.Heartbeat(d, 2, interval_s=0.0)
    hb.beat(step=5, blocked_s=0.73, force=True)
    doc = elastic.read_heartbeats(d)[2]
    assert doc["blocked_s"] == pytest.approx(0.73)
    # no new step → the stale wait must not overwrite the step's sample
    hb.beat(step=5, blocked_s=9.9, force=True)
    assert elastic.read_heartbeats(d)[2]["blocked_s"] == pytest.approx(0.73)


def test_worker_page_survives_beacon_outage(tmp_path):
    logs = []
    ep = elastic.Epoch(epoch=0, members=[0], port=1)
    w = elastic.ElasticWorker(str(tmp_path), 0, ep, log=logs.append)
    w.heartbeat._write = _raise_oserror     # total beacon outage
    w.page("grad-explosion:decoder")        # must not raise
    assert any("health page publish failed" in l for l in logs)


def _raise_oserror(*a, **k):
    raise OSError("disk gone")


class _FakeSentry:
    def __init__(self):
        self.on_breach = None


class _Breach:
    detector = "nan-precursor"
    group = "transformer"


def test_install_breach_pager_chains_existing_sink(tmp_path):
    ep = elastic.Epoch(epoch=0, members=[0, 1], port=1)
    w = elastic.ElasticWorker(str(tmp_path), 1, ep)
    sentry = _FakeSentry()
    seen = []
    sentry.on_breach = seen.append          # a pre-existing BreachActions
    install_breach_pager(w, sentry)
    sentry.on_breach(_Breach())
    assert len(seen) == 1                   # the original sink still fired
    doc = elastic.read_heartbeats(str(tmp_path))[1]
    assert doc["page"] == "nan-precursor:transformer"


def test_agent_drains_health_paged_worker_and_respawns(tmp_path):
    """End to end over jax-free children: a worker that pages via its
    heartbeat is drained by the agent's ladder and QUARANTINE-RESPAWNED —
    same slot, fresh process — with the degrade_drain event recorded."""
    import subprocess
    import sys
    run_dir = str(tmp_path / "pod")
    os.makedirs(run_dir, exist_ok=True)
    script = tmp_path / "child.py"
    script.write_text(CHILD_PAGING)

    def spawn(worker_id, epoch):
        return subprocess.Popen(
            [sys.executable, str(script), run_dir, str(worker_id),
             str(epoch.epoch)])

    agent = elastic.ElasticAgent(
        run_dir, spawn, members=[0, 1], poll_s=0.05, term_grace_s=3.0,
        degrade=DegradeMonitor(StragglerDetector()))
    events = agent.run(deadline_s=60)
    kinds = [e["kind"] for e in events]
    assert any(e["kind"] == "worker_paged" and e.get("worker") == 1
               and e.get("reason") == "health_page" for e in events)
    assert any(e["kind"] == "degrade_drain" and e.get("worker") == 1
               for e in events)
    # quarantine-respawn: the paged worker KEEPS its slot (fresh process)
    assert agent.epoch.members == [0, 1]
    assert kinds[-1] == "pod_done"


CHILD_PAGING = """
import json, os, sys, time
run_dir, wid, epoch = sys.argv[1], sys.argv[2], int(sys.argv[3])
def beat(page=None):
    p = os.path.join(run_dir, f"hb_{wid}.json")
    tmp = p + ".tmp"
    json.dump({"worker_id": int(wid), "pid": os.getpid(),
               "time": time.time(), "page": page}, open(tmp, "w"))
    os.replace(tmp, p)
beat()
# epoch 0: worker 1's sentry breaches -> page rides the heartbeat; the
# agent should drain (SIGTERM) and respawn us into epoch 1, where we run
# clean to completion
if wid == "1" and epoch == 0:
    for _ in range(100):
        beat(page="nan-precursor:transformer"); time.sleep(0.05)
    sys.exit(0)
for _ in range(4):
    beat(); time.sleep(0.05)
sys.exit(0)
"""
