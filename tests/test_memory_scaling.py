"""Compiled-program memory assertions for the memory levers (VERDICT r2 weak
#2): the claims "fsdp shards the model state" and "loss_chunk caps the logits
memory" are measured on `jax.jit(...).lower().compile().memory_analysis()`,
not just asserted as math equality. The ring/sp lever has its own assertions
in test_ring_attention.py::test_kernel_ring_memory_scales."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_tpu.config import (DalleConfig, MeshConfig, OptimConfig,
                              PrecisionConfig, TrainConfig)
from dalle_tpu.models.dalle import init_dalle
from dalle_tpu.parallel import shard_batch
from dalle_tpu.parallel.mesh import build_mesh
from dalle_tpu.train.trainer_dalle import DalleTrainer


def _loss_bwd_temp(loss_chunk: int) -> int:
    """Temp bytes of the compiled fwd+bwd for a config whose vocab head
    dominates (16k vocab, dim 128)."""
    cfg = DalleConfig(num_text_tokens=30000, text_seq_len=128, dim=128,
                      depth=1, heads=2, dim_head=64, image_size=32,
                      image_vocab_size=8192, image_fmap_size=8,
                      loss_chunk=loss_chunk)
    model, params = init_dalle(cfg, jax.random.PRNGKey(0))
    text = jnp.zeros((16, cfg.text_seq_len), jnp.int32)
    ids = jnp.zeros((16, cfg.image_seq_len), jnp.int32)

    def f(params):
        loss, _ = model.apply(params, text, ids, return_loss=True)
        return loss

    c = jax.jit(jax.grad(f)).lower(params).compile()
    return c.memory_analysis().temp_size_in_bytes


@pytest.mark.slow  # ~14s (two big-vocab fwd+bwd compiles); chunked-loss
# EXACTNESS stays fast-tier in test_dalle::test_chunked_loss_matches_full —
# the bwd-temp-bytes ledger assertion rides the slow tier
def test_loss_chunk_caps_logits_memory():
    """Chunked vocab-head CE must shrink the backward's temp footprint by at
    least 0.6x of one full (b, n, vocab) logits materialization (~471MB f32
    at b16, n=192, vocab 38,320). Absolute delta, not a ratio: the CPU
    backend's buffer scheduling keeps a large config-independent floor that
    would mask a ratio assertion."""
    dense = _loss_bwd_temp(0)
    chunked = _loss_bwd_temp(32)
    logits_bytes = 16 * (128 + 64) * (30000 + 128 + 8192) * 4
    assert chunked < dense - 0.6 * logits_bytes, (dense, chunked, logits_bytes)


def _step_memory(mesh_cfg: MeshConfig, tmpdir):
    cfg = DalleConfig(num_text_tokens=512, text_seq_len=16, dim=256, depth=2,
                      heads=4, dim_head=64, image_size=32,
                      image_vocab_size=512, image_fmap_size=4)
    tc = TrainConfig(batch_size=8, checkpoint_dir=str(tmpdir),
                     preflight_checkpoint=False, mesh=mesh_cfg,
                     precision=PrecisionConfig(compute="float32"),
                     optim=OptimConfig(learning_rate=1e-3))
    tr = DalleTrainer(cfg, tc, mesh=build_mesh(mesh_cfg))
    text = shard_batch(tr.mesh, np.zeros((8, 16), np.int32))
    ids = shard_batch(tr.mesh, np.zeros((8, 16), np.int32))
    c = tr.step_fn.lower(tr.state, text, ids,
                         jax.random.PRNGKey(0)).compile()
    m = c.memory_analysis()
    return m.argument_size_in_bytes, m.temp_size_in_bytes


def test_fsdp_shards_state_memory(tmp_path):
    """fsdp=8 must shrink the per-device state (params + opt moments live
    sharded): compiled argument bytes well below the replicated dp=8 run."""
    rep_args, _ = _step_memory(MeshConfig(dp=8), tmp_path / "dp")
    fsdp_args, _ = _step_memory(MeshConfig(dp=1, fsdp=8), tmp_path / "fsdp")
    # batch args are identical; params/opt (the dominant share) shard 1/8
    assert fsdp_args < 0.45 * rep_args, (rep_args, fsdp_args)
