"""graftfleet (dalle_tpu/fleet): frame transport, RemoteReplica streaming
and failover, the autoscaling controller's hysteresis/bounds, the FLEET
report verdict, and AOT fingerprint refusal across real processes.

Most tests run over a FAKE engine (pure host code, deterministic tokens,
a semaphore pacing rows) so transport and control-loop semantics are
tested without jax compiles; one module-fixture section pins the bitwise
contract over a real engine, and one subprocess test pins the cross-
process AOT refusal satellite.
"""

import importlib.util
import json
import os
import socket
import threading
import time
import types

import numpy as np
import pytest

# ceiling = measured cold full-run total (165 — all of it in the one
# real-engine bitwise test: module model + refs + engine programs + the
# shared-prefix group path; every fake-engine transport/controller test
# measures 0) + ~15% cross-jax-version slack (the test_serve convention).
pytestmark = pytest.mark.recompile_budget(190)

SCRIPTS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts")


def _load_script(name):
    import sys
    if SCRIPTS not in sys.path:
        sys.path.insert(0, SCRIPTS)
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(SCRIPTS, f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture()
def tracer():
    from dalle_tpu import obs
    tr = obs.configure()
    yield tr
    obs.disable()


# ---------------------------------------------------------------------------
# frame protocol
# ---------------------------------------------------------------------------

def test_frame_roundtrip_and_torn_frame():
    from dalle_tpu.fleet import TransportError, recv_frame, send_frame
    a, b = socket.socketpair()
    try:
        send_frame(a, {"verb": "health", "x": [1, 2, 3]})
        assert recv_frame(b, timeout=2.0) == {"verb": "health",
                                              "x": [1, 2, 3]}
        # clean EOF → None
        a.close()
        assert recv_frame(b, timeout=2.0) is None
    finally:
        b.close()
    # torn frame (length promised, connection dies mid-body) must raise,
    # never silently truncate
    a, b = socket.socketpair()
    try:
        a.sendall(b"\x00\x00\x00\x10{\"par")
        a.close()
        with pytest.raises(TransportError, match="torn frame"):
            recv_frame(b, timeout=2.0)
    finally:
        b.close()


def test_frame_timeout_raises():
    from dalle_tpu.fleet import recv_frame
    a, b = socket.socketpair()
    try:
        with pytest.raises(TimeoutError):
            recv_frame(b, timeout=0.1)
    finally:
        a.close()
        b.close()


def test_oversize_length_prefix_is_a_protocol_error(tracer):
    """A corrupt/hostile 4-byte length beyond MAX_FRAME_BYTES raises a
    clear TransportError BEFORE any allocation is attempted, on both the
    one-shot reader and the buffered stream reader, and each counts as
    fleet.protocol_errors_total{kind=oversize_frame}."""
    from dalle_tpu import obs
    from dalle_tpu.fleet import TransportError, recv_frame
    from dalle_tpu.fleet.transport import _LEN, MAX_FRAME_BYTES, _FrameReader
    a, b = socket.socketpair()
    try:
        a.sendall(_LEN.pack(MAX_FRAME_BYTES + 1))
        with pytest.raises(TransportError, match="exceeds"):
            recv_frame(b, timeout=2.0)
    finally:
        a.close()
        b.close()
    a, b = socket.socketpair()
    try:
        a.sendall(_LEN.pack(MAX_FRAME_BYTES + 1))
        with pytest.raises(TransportError, match="exceeds"):
            _FrameReader(b).read(timeout=2.0)
    finally:
        a.close()
        b.close()
    snap = obs.metrics_snapshot()
    assert snap['fleet.protocol_errors_total{kind="oversize_frame"}'] == 2


def test_truncated_frame_mid_payload_counts_torn(tracer):
    """A connection dying mid-body raises (never silently truncates) on
    both readers and counts as protocol_errors_total{kind=torn_frame}."""
    from dalle_tpu import obs
    from dalle_tpu.fleet import TransportError, recv_frame
    from dalle_tpu.fleet.transport import _FrameReader
    for reader in (lambda s: recv_frame(s, timeout=2.0),
                   lambda s: _FrameReader(s).read(timeout=2.0)):
        a, b = socket.socketpair()
        try:
            a.sendall(b"\x00\x00\x00\x10{\"par")
            a.close()
            with pytest.raises(TransportError, match="torn frame"):
                reader(b)
        finally:
            b.close()
    snap = obs.metrics_snapshot()
    assert snap['fleet.protocol_errors_total{kind="torn_frame"}'] == 2


def test_undecodable_frame_body_counts_bad_json(tracer):
    from dalle_tpu import obs
    from dalle_tpu.fleet import TransportError, recv_frame
    from dalle_tpu.fleet.transport import _LEN, _FrameReader
    body = b"}{ not json"
    for reader in (lambda s: recv_frame(s, timeout=2.0),
                   lambda s: _FrameReader(s).read(timeout=2.0)):
        a, b = socket.socketpair()
        try:
            a.sendall(_LEN.pack(len(body)) + body)
            with pytest.raises(TransportError, match="undecodable"):
                reader(b)
        finally:
            a.close()
            b.close()
    snap = obs.metrics_snapshot()
    assert snap['fleet.protocol_errors_total{kind="bad_json"}'] == 2


def test_unknown_verb_typed_error_and_counter(remote_pair):
    """A verb the server does not dispatch draws the unknown_verb error
    ack; the client surfaces a TYPED ReplicaFailure promptly (no hung
    RemoteReplica waiting on a stream) and the protocol-error counter
    records the disagreement."""
    from dalle_tpu import obs
    from dalle_tpu.fleet import call
    from dalle_tpu.fleet.transport import RemoteResultStream
    from dalle_tpu.gateway.replica import ReplicaFailure
    _rep, srv, rem = remote_pair()
    assert call(srv.addr, {"verb": "bogus"}) == {"error": "unknown_verb",
                                                 "detail": "bogus"}
    t0 = time.monotonic()
    with pytest.raises(ReplicaFailure, match="unknown_verb"):
        rem._open_stream({"verb": "bogus"}, RemoteResultStream)
    assert time.monotonic() - t0 < 5.0
    snap = obs.metrics_snapshot()
    assert snap['fleet.protocol_errors_total{kind="unknown_verb"}'] == 1


def test_handshake_refusal_typed_error_and_counter(tracer):
    """A replica process that exits before its handshake surfaces as a
    typed SpawnError naming the exit code — not a hang — and counts as
    fleet.protocol_errors_total{kind=handshake}."""
    import subprocess
    import sys
    from dalle_tpu import obs
    from dalle_tpu.fleet import SpawnError
    from dalle_tpu.fleet.manager import _read_handshake
    proc = subprocess.Popen(
        [sys.executable, "-c",
         "print('refusing to serve'); raise SystemExit(7)"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    try:
        with pytest.raises(SpawnError, match="before handshake"):
            _read_handshake(proc, timeout_s=10.0)
    finally:
        proc.wait(timeout=10)
        proc.stdout.close()
    snap = obs.metrics_snapshot()
    assert snap['fleet.protocol_errors_total{kind="handshake"}'] == 1


# ---------------------------------------------------------------------------
# fake engine: deterministic tokens, semaphore-paced rows — lets transport
# and failover tests hold a stream open without a device in sight
# ---------------------------------------------------------------------------

class FakeEngine:
    N_STEPS = 8
    ROW_LEN = 4

    def __init__(self, slots=2, gate=None):
        self.slots = slots
        self.n_steps = self.N_STEPS
        self.row_len = self.ROW_LEN
        self.gate = gate            # Semaphore: one acquire per row
        self.aot_loaded = False

    @staticmethod
    def tokens_for(seed, n=N_STEPS):
        return [(seed * 31 + i) % 97 for i in range(n)]

    def run(self, queue, on_complete=None, on_rows=None):
        from dalle_tpu.serve.queue import CompletedRequest
        while not queue.drained:
            reqs = queue.take(self.slots)
            if not reqs:
                queue.wait_nonempty(timeout=0.02)
                continue
            for req in reqs:
                admitted = time.perf_counter()
                req.admitted_at = admitted
                n = min(req.max_tokens or self.n_steps, self.n_steps)
                toks = self.tokens_for(req.seed, n)
                first = None
                for row in range((n + self.row_len - 1) // self.row_len):
                    if self.gate is not None:
                        self.gate.acquire()
                    if first is None:
                        first = time.perf_counter()
                    chunk = toks[row * self.row_len:
                                 (row + 1) * self.row_len]
                    if on_rows is not None:
                        on_rows(req, row, chunk)
                if on_complete is not None:
                    on_complete(CompletedRequest(
                        request_id=req.request_id,
                        tokens=np.asarray(toks, np.int32), seed=req.seed,
                        submitted_at=req.submitted_at, admitted_at=admitted,
                        first_token_at=first,
                        completed_at=time.perf_counter()))


@pytest.fixture()
def remote_pair(tracer):
    """A served fake replica + its RemoteReplica, torn down after."""
    from dalle_tpu.fleet import RemoteReplica, ReplicaServer
    from dalle_tpu.gateway import Replica
    made = []

    def make(gate=None, maxsize=16, heartbeat_s=0.1):
        rep = Replica(FakeEngine(gate=gate), maxsize=maxsize).start()
        srv = ReplicaServer(rep).start()
        rem = RemoteReplica(srv.addr, replica_id=rep.replica_id,
                            heartbeat_s=heartbeat_s)
        made.append((rep, srv, rem))
        return rep, srv, rem
    yield make
    for rep, srv, rem in made:
        rem.close()
        srv.shutdown()
        rep.queue.close()


TEXT = np.array([1, 2, 3], np.int32)


def test_remote_submit_streams_rows_and_done(remote_pair):
    _rep, _srv, rem = remote_pair()
    stream = rem.submit(TEXT, seed=7)
    rows, done = [], None
    for kind, payload in stream.events(timeout=10.0):
        if kind == "row":
            rows.append(payload)
        elif kind == "done":
            done = payload
    want = FakeEngine.tokens_for(7)
    assert [r for r, _t in rows] == [0, 1]
    assert [t for _r, chunk in rows for t in chunk] == want
    assert done is not None and done.tokens == want
    assert done.latency_s >= 0.0
    # the wire done frame carries the replica-measured slot time — the
    # gateway-side SloEstimator's feed for REMOTE completions (graftward
    # satellite); bounded by the submit→done latency by construction
    assert 0.0 <= done.decode_s <= done.latency_s + 1e-6


def test_remote_health_load_and_graceful_drain(remote_pair):
    from dalle_tpu import obs
    rep, srv, rem = remote_pair()
    time.sleep(0.25)                      # a heartbeat lands
    h = rem.health()
    assert h["healthy"] and h["remote"] and h["slots"] == 2
    assert h["image_seq_len"] == FakeEngine.N_STEPS
    assert h["requests_served"] == 0 and h["pid"] == os.getpid()
    assert rem.load == 0
    # the decode-quality dict uses the BARE stat names the controller's
    # _degraded predicate reads (the in-process server shares this obs
    # registry, so the real gauge → health-verb path is exercised)
    obs.gauge_set("health.decode_repeat_ratio", 0.75)
    obs.gauge_set("health.decode_entropy", 0.4)
    from dalle_tpu.fleet import call
    fresh = call(srv.addr, {"verb": "health"})
    assert fresh["decode"] == {"repeat_ratio": 0.75, "entropy": 0.4}
    from dalle_tpu.fleet import FleetController
    ctl = FleetController.__new__(FleetController)
    ctl.drain_repeat_ratio, ctl.drain_entropy_floor = 0.5, None
    assert "decode_repeat_ratio" in ctl._degraded(fresh)
    rem.drain(timeout=10.0)
    assert not rem.healthy                # draining replicas leave dispatch
    assert rep.queue.closed


def test_remote_group_submit_multiplexes_candidates(remote_pair):
    _rep, _srv, rem = remote_pair()
    group = rem.submit_group(TEXT, seeds=[3, 4])
    done = {}
    rows = {0: [], 1: []}
    for idx, kind, payload in group.events(timeout=10.0):
        if kind == "row":
            rows[idx].extend(payload[1])
        elif kind == "done":
            done[idx] = payload
    assert done[0].tokens == FakeEngine.tokens_for(3)
    assert done[1].tokens == FakeEngine.tokens_for(4)
    assert rows[0] == FakeEngine.tokens_for(3)


def test_remote_queue_full_maps_to_queue_full(remote_pair):
    from dalle_tpu.serve.queue import QueueFull
    _rep, _srv, rem = remote_pair(maxsize=1)
    with pytest.raises(QueueFull):
        rem.submit_group(TEXT, seeds=[1, 2, 3])


def test_remote_worker_death_relays_reason(remote_pair):
    rep, _srv, rem = remote_pair()
    rep.fail_after_rows(1)
    stream = rem.submit(TEXT, seed=9)
    events = list(stream.events(timeout=10.0))
    assert events[-1][0] == "replica_failed"
    payload = events[-1][1]
    assert isinstance(payload, dict) and payload["reason"] == "worker_death"


def test_router_failover_across_migrate_is_exact(remote_pair, tracer):
    """The drain/migrate hand-off, end to end over the wire: victim paced
    by a semaphore, migrated mid-stream; the router resubmits to the
    standby and the spliced stream is exactly the uninterrupted tokens,
    each row once — with the failover labeled by its reason."""
    from dalle_tpu import obs
    from dalle_tpu.gateway import ReplicaRouter
    gate = threading.Semaphore(1)         # row 0 passes, row 1 blocks
    _vrep, _vsrv, victim = remote_pair(gate=gate)
    _srep, _ssrv, standby = remote_pair()
    router = ReplicaRouter([victim, standby])
    routed = router.submit(TEXT, seed=11)
    assert routed.replica_id == victim.replica_id
    rows, done_box = [], [None]
    first_row = threading.Event()

    def consume():
        for kind, payload in routed.events(timeout=10.0):
            if kind == "row":
                rows.append(payload)
                first_row.set()
            elif kind == "done":
                done_box[0] = payload
        first_row.set()
    t = threading.Thread(target=consume)
    t.start()
    assert first_row.wait(5.0) and done_box[0] is None
    assert victim.migrate(reason="health_page") == 1
    gate.release()                        # let the (now unobserved) fake go
    gate.release()
    t.join(timeout=20.0)
    done = done_box[0]
    assert done is not None and done["failovers"] == 1
    assert done["tokens"] == FakeEngine.tokens_for(11)
    assert done["replica"] == standby.replica_id
    assert [p["row"] for p in rows] == [0, 1]     # each row exactly once
    snap = obs.metrics_snapshot()
    assert snap.get('gateway.failover_total{reason="health_page"}') == 1.0
    assert snap.get("gateway.failovers_total") == 1.0


def test_router_add_remove_replica_dynamic_membership(remote_pair):
    from dalle_tpu.gateway import ReplicaRouter
    _r1, _s1, rem1 = remote_pair()
    _r2, _s2, rem2 = remote_pair()
    router = ReplicaRouter([rem1])
    router.add_replica(rem2)
    assert len(router.replicas) == 2
    assert router.remove_replica(rem1.replica_id) is rem1
    assert router.replicas == [rem2]
    assert router.remove_replica("no-such") is None


# ---------------------------------------------------------------------------
# controller: hysteresis, cooldown, bounds, repair, degradation drains
# ---------------------------------------------------------------------------

class FakeRemote:
    def __init__(self, rid):
        self.replica_id = rid
        self.healthy = True
        self.load = 0
        self.missed_heartbeats = 0
        self.max_missed = 3
        self.health_doc = {"decode": {}}
        self.migrations = []

    def health(self):
        return self.health_doc

    def migrate(self, reason):
        self.migrations.append(reason)
        return 1

    def drain(self, timeout=None):
        pass

    def close(self):
        pass


class FakeProc:
    _seq = [0]

    def __init__(self):
        FakeProc._seq[0] += 1
        self.remote = FakeRemote(f"fake-{FakeProc._seq[0]}")
        self.alive = True
        self.handshake = {"aot_loaded": True, "backend_compiles": 0}
        self.pid = 10000 + FakeProc._seq[0]

    @property
    def replica_id(self):
        return self.remote.replica_id

    def kill(self, sig=None):
        self.alive = False


class FakeManager:
    def __init__(self):
        self.killed = []
        self.stopped = []
        self.spawned = 0
        self.fail_next = 0

    @property
    def warm_available(self):
        return 1

    def acquire(self):
        if self.fail_next > 0:
            self.fail_next -= 1
            from dalle_tpu.fleet import SpawnError
            raise SpawnError("injected spawn failure")
        self.spawned += 1
        return FakeProc()

    def kill(self, rp, sig=None):
        rp.kill()
        self.killed.append(rp.replica_id)

    def stop(self, rp, drain_timeout_s=None):
        rp.kill()
        self.stopped.append(rp.replica_id)


def _ctl(n=1, **kw):
    from dalle_tpu.fleet import FleetController
    from dalle_tpu.gateway import ReplicaRouter
    procs = [FakeProc() for _ in range(n)]
    router = ReplicaRouter([rp.remote for rp in procs])
    mgr = FakeManager()
    burn = {"v": False}
    sentry = types.SimpleNamespace(evaluate=lambda: {"burning": burn["v"]})
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 3)
    kw.setdefault("up_sustain", 2)
    kw.setdefault("down_sustain", 3)
    kw.setdefault("cooldown_ticks", 3)
    kw.setdefault("retire_grace_ticks", 0)
    ctl = FleetController(router, mgr, sentry=sentry, **kw)
    for rp in procs:
        ctl.adopt(rp)
    return ctl, router, mgr, burn, procs


def test_scale_up_needs_sustain_and_respects_cooldown_and_max(tracer):
    ctl, router, mgr, burn, _ = _ctl()
    burn["v"] = True
    assert ctl.tick() == []                       # streak 1 < up_sustain 2
    acts = ctl.tick()
    assert [d["action"] for d in acts] == ["scale_up"]
    assert acts[0]["reason"] == "slo_burn"
    assert len(router.replicas) == 2
    # still burning, but cooldown holds the fleet still (ticks 3 and 4)
    assert ctl.tick() == [] and ctl.tick() == []
    # cooldown over + streak re-sustained → second scale_up, then the
    # max bound pins the fleet
    assert [d["action"] for d in ctl.tick()] == ["scale_up"]
    assert len(router.replicas) == 3
    for _ in range(8):
        ctl.tick()
    assert len(router.replicas) == 3              # max_replicas bound


def test_scale_down_on_sustained_idle_bounded_by_min(tracer):
    ctl, router, mgr, _burn, _ = _ctl(n=2)
    for _ in range(2):
        assert ctl.tick() == []                   # idle streak building
    acts = ctl.tick()
    assert [d["action"] for d in acts] == ["scale_down"]
    assert len(router.replicas) == 1
    for _ in range(10):
        ctl.tick()
    assert len(router.replicas) == 1              # min_replicas bound


def test_oscillating_pressure_never_flaps(tracer):
    ctl, router, _mgr, burn, _ = _ctl(down_sustain=4)
    for i in range(12):                           # burn flips every tick
        burn["v"] = i % 2 == 0
        ctl.tick()
    assert ctl.decisions == []                    # hysteresis holds


def test_replace_on_missed_heartbeats_ignores_cooldown(tracer):
    ctl, router, mgr, burn, procs = _ctl(n=2)
    procs[0].remote.missed_heartbeats = 3
    acts = ctl.tick()
    assert [d["action"] for d in acts] == ["replace", "replace"]
    assert acts[0]["replica"] == procs[0].replica_id
    assert procs[0].replica_id in mgr.killed
    assert len(router.replicas) == 2              # capacity restored
    assert procs[0].remote not in router.replicas


def test_min_bound_reconciles_after_failed_replacement(tracer):
    """A replacement spawn failing at the moment of the replace must not
    leave the fleet undersized forever — later ticks retry until the min
    bound holds again (with zero replicas nothing generates burn pressure,
    so nothing else would ever restore capacity)."""
    ctl, router, mgr, _burn, procs = _ctl(n=1)
    # the repair-path attach, the same tick's reconciliation retry, and
    # the next tick's retry all fail before the manager heals
    mgr.fail_next = 3
    procs[0].alive = False
    acts = ctl.tick()
    assert [d["action"] for d in acts] == ["replace", "spawn_failed",
                                           "spawn_failed"]
    assert len(router.replicas) == 0  # transiently below min
    assert [d["action"] for d in ctl.tick()] == ["spawn_failed"]
    acts = ctl.tick()                 # manager healed → bound restored
    assert [d["action"] for d in acts] == ["replace"] \
        and acts[0]["reason"] == "below_min"
    assert len(router.replicas) == 1


def test_replace_on_process_exit(tracer):
    ctl, router, mgr, _burn, procs = _ctl(n=1)
    procs[0].alive = False
    acts = ctl.tick()
    assert acts[0]["action"] == "replace" \
        and acts[0]["reason"] == "process_exit"
    assert len(router.replicas) == 1


def test_draining_replica_is_not_mistaken_for_zombie(tracer):
    """Deliberate drains (gateway shutdown, operator) flip healthy to
    False while heartbeats stay fresh — the repair loop must leave them
    alone, not SIGKILL accepted work mid-graceful-drain."""
    ctl, router, mgr, _burn, procs = _ctl(n=2)
    procs[0].remote.healthy = False
    procs[0].remote.draining = True
    assert ctl.tick() == []
    assert procs[0].replica_id not in mgr.killed


def test_scale_up_spawn_failure_retries_without_phantom_cooldown(tracer):
    """A failed scale-up attach must not burn streak/cooldown — the
    retry fires on the very next tick while the burn persists."""
    ctl, router, mgr, burn, _ = _ctl()
    burn["v"] = True
    mgr.fail_next = 1
    ctl.tick()
    acts = ctl.tick()                     # streak reached; spawn fails
    assert [d["action"] for d in acts] == ["spawn_failed"]
    acts = ctl.tick()                     # immediate retry, no cooldown
    assert [d["action"] for d in acts] == ["scale_up"]
    assert len(router.replicas) == 2


def test_replace_zombie_replica_alive_but_unhealthy(tracer):
    """A process that answers heartbeats while its engine worker is dead
    (health reports healthy=false) is counted-but-serving-nothing
    capacity — the repair loop must replace it, not trust liveness."""
    ctl, router, mgr, _burn, procs = _ctl(n=2)
    procs[0].remote.healthy = False
    acts = ctl.tick()
    assert acts[0]["action"] == "replace" \
        and acts[0]["reason"] == "replica_unhealthy"
    assert procs[0].replica_id in mgr.killed
    assert len(router.replicas) == 2


def test_drain_on_sustained_decode_degradation(tracer):
    ctl, router, mgr, _burn, procs = _ctl(
        n=2, drain_repeat_ratio=0.5, health_sustain=3)
    bad = procs[0]
    bad.remote.health_doc = {"decode": {"repeat_ratio": 0.9}}
    assert ctl.tick() == [] and ctl.tick() == []  # sustain window
    acts = ctl.tick()
    # reason stays a BOUNDED label token; the measured value rides detail
    assert acts[0]["action"] == "drain" \
        and acts[0]["reason"] == "decode_degraded" \
        and "decode_repeat_ratio" in acts[0]["detail"]
    assert bad.remote.migrations and len(router.replicas) == 1
    ctl.tick()                                    # grace 0 → reap now
    assert bad.replica_id in mgr.killed
    # a recovered replica's streak resets instead of accumulating
    good = procs[1]
    good.remote.health_doc = {"decode": {"repeat_ratio": 0.9}}
    ctl.tick()
    good.remote.health_doc = {"decode": {"repeat_ratio": 0.0}}
    for _ in range(6):
        ctl.tick()
    assert not good.remote.migrations


def test_request_drain_and_below_min_replacement(tracer):
    ctl, router, mgr, _burn, procs = _ctl(n=1)
    ctl.request_drain(procs[0].replica_id, reason="health_page")
    acts = ctl.tick()
    assert acts[0]["action"] == "drain" \
        and acts[0]["reason"] == "health_page"
    # fleet fell below min → a replacement attached in the same tick
    assert any(d["action"] == "replace" for d in acts)
    assert len(router.replicas) == 1
    assert procs[0].remote.migrations == ["health_page"]


def test_every_decision_within_bounds_and_counted(tracer):
    from dalle_tpu import obs
    ctl, router, _mgr, burn, procs = _ctl(n=2, down_sustain=2)
    burn["v"] = True
    for _ in range(6):
        ctl.tick()
    burn["v"] = False
    procs[0].remote.missed_heartbeats = 3
    for _ in range(8):
        ctl.tick()
    assert ctl.decisions
    assert all(ctl.min_replicas <= d["fleet"] <= ctl.max_replicas
               for d in ctl.decisions)
    snap = obs.metrics_snapshot()
    for action in {d["action"] for d in ctl.decisions}:
        key = f'fleet.actions_total{{action="{action}"}}'
        assert snap[key] == sum(
            1 for d in ctl.decisions if d["action"] == action)
    assert "fleet.size" in snap and "fleet.state" in snap


# ---------------------------------------------------------------------------
# obs_report: FLEET verdict + failover attribution
# ---------------------------------------------------------------------------

def test_fleet_accounting_and_verdict_line():
    from dalle_tpu.obs.report import fleet_accounting, format_report
    rows = [{"step": 0, "fleet.size": 2.0, "fleet.warm_pool": 1.0,
             "fleet.state": 1.0,
             'fleet.actions_total{action="scale_up"}': 1.0,
             'fleet.actions_total{action="drain"}': 2.0}]
    fl = fleet_accounting(rows)
    assert fl["verdict"] == "scaling"
    assert fl["actions"] == {"scale_up": 1, "drain": 2}
    out = format_report(rows)
    assert "FLEET: scaling" in out and "fleet (graftfleet)" in out
    rows[0]["fleet.state"] = 2.0
    assert "FLEET: draining" in format_report(rows)
    rows[0]["fleet.state"] = 0.0
    assert "FLEET: steady" in format_report(rows)
    assert fleet_accounting([{"step": 0, "gateway.inflight": 1.0}]) is None


def test_gateway_accounting_attributes_failovers_by_reason():
    from dalle_tpu.obs.report import format_report, gateway_accounting
    rows = [{"step": 0, "gateway.inflight": 0.0,
             "gateway.failovers_total": 3.0,
             'gateway.failover_total{reason="conn_reset"}': 2.0,
             'gateway.failover_total{reason="health_page"}': 1.0}]
    gw = gateway_accounting(rows, [])
    assert gw["failover_reasons"] == {"conn_reset": 2, "health_page": 1}
    out = format_report(rows)
    assert "by reason" in out and "conn_reset" in out


# ---------------------------------------------------------------------------
# graftward: wedge drains + the DEGRADE verdict
# ---------------------------------------------------------------------------

def test_controller_drains_wedged_self_report(tracer):
    """A replica whose health verb self-reports wedged rides the DRAIN
    path (migrate → streams fail over with reason="wedged" → splice), not
    the blind replace path — and only once (the drain detaches it)."""
    ctl, router, mgr, burn, procs = _ctl(n=2)
    victim = procs[0]
    victim.remote.health_doc["wedged"] = True
    acts = ctl.tick()
    assert [d["action"] for d in acts] == ["drain"]
    assert acts[0]["reason"] == "wedged"
    assert victim.remote.migrations == ["wedged"]
    assert len(router.replicas) == 1
    assert all(d["action"] != "drain" for d in ctl.tick())
    from dalle_tpu.obs import metrics_snapshot
    assert metrics_snapshot()[
        'degrade.actions_total{reason="wedged"}'] == 1.0


def test_controller_drains_on_outside_in_progress_stall(tracer):
    """The transport-side frozen-progress check (satellite of the wedge
    work: fresh heartbeats + frozen iteration counter ≠ healthy idle) is
    the backstop when the replica's own watchdog is off — same drain,
    same reason label."""
    ctl, router, mgr, burn, procs = _ctl(n=2)
    victim = procs[1]
    victim.remote.progress_stalled = True
    acts = ctl.tick()
    assert [(d["action"], d["reason"]) for d in acts] == [
        ("drain", "wedged")]
    assert victim.remote.migrations == ["wedged"]


def test_remote_progress_stall_semantics(remote_pair):
    """RemoteReplica._track_progress reuses elastic.py's fresh-but-frozen
    logic: busy + frozen counter past the timeout = stalled; idle or
    advancing counters never stall; progress resuming clears the latch;
    and a counter that never advanced (first compile) never arms."""
    rep, srv, rem = remote_pair()
    rem.progress_timeout_s = 0.05
    # never-advanced counter (progress 0: first-dispatch compile): busy +
    # frozen forever, but NOT armed — the counter's VALUE is the gate
    rem._track_progress({"progress": 0, "inflight": 1})
    time.sleep(0.12)
    rem._track_progress({"progress": 0, "inflight": 1})
    assert not rem.progress_stalled
    # a wedge at the FIRST value this monitor ever observes (attach to a
    # warmed replica, first request wedges) must still arm and stall —
    # witnessing a change between polls is NOT required
    rem._track_progress({"progress": 2, "inflight": 1})
    time.sleep(0.12)
    rem._track_progress({"progress": 2, "inflight": 1})
    assert rem.progress_stalled
    # progress resuming clears the latch
    rem._track_progress({"progress": 3, "inflight": 1})
    assert not rem.progress_stalled
    # idle with a frozen counter is just idle — never a stall
    rem._track_progress({"progress": 3, "inflight": 0,
                         "queue_depth": 0})
    time.sleep(0.12)
    rem._track_progress({"progress": 3, "inflight": 0,
                         "queue_depth": 0})
    assert not rem.progress_stalled
    # disabled timeout (the default): inert even when busy + frozen
    rem2_rep, _, rem2 = remote_pair()
    rem2._track_progress({"progress": 2, "inflight": 1})
    rem2._track_progress({"progress": 3, "inflight": 1})
    time.sleep(0.12)
    rem2._track_progress({"progress": 3, "inflight": 1})
    assert not rem2.progress_stalled


def test_wedge_self_report_rides_health_verb(remote_pair):
    rep, srv, rem = remote_pair(heartbeat_s=0.05)
    rep.mark_wedged("chaos wedge at iteration 9")
    deadline = time.time() + 5.0
    while time.time() < deadline and not rem.health().get("wedged"):
        time.sleep(0.05)
    h = rem.health()
    assert h["wedged"] and h["reason"] == "wedged"
    assert not h["healthy"] and not rem.healthy
    assert "iteration 9" in h["wedge_detail"]


def test_degrade_accounting_and_verdict_line():
    from dalle_tpu.obs.report import degrade_accounting, format_report
    rows = [{"step": 0,
             'degrade.pages_total{reason="straggler"}': 1.0,
             'degrade.actions_total{reason="straggler"}': 1.0,
             'degrade.actions_total{reason="wedged"}': 2.0,
             "degrade.wedged_total": 2.0}]
    dg = degrade_accounting(rows)
    assert dg["verdict"] == "responded"
    assert dg["actions"] == {"straggler": 1, "wedged": 2}
    assert dg["pages"] == {"straggler": 1} and dg["wedged"] == 2
    out = format_report(rows)
    assert "DEGRADE: responded" in out and "wedged" in out
    # pages without actions: detected but never escalated
    paged = [{"step": 0, 'degrade.pages_total{reason="health_page"}': 1.0}]
    assert degrade_accounting(paged)["verdict"] == "paged"
    assert "DEGRADE: paged" in format_report(paged)
    # no degrade keys at all: the report is unchanged
    assert degrade_accounting([{"step": 0, "fleet.size": 1.0}]) is None


# ---------------------------------------------------------------------------
# real engine: the bitwise contract over the wire
# ---------------------------------------------------------------------------

CFG = dict(num_text_tokens=32, text_seq_len=6, dim=32, depth=2, heads=2,
           dim_head=16, image_size=16, image_vocab_size=24,
           image_fmap_size=4)
TEXTS = [np.array([3, 4, 5, 0, 0, 0], np.int32),
         np.array([7, 8, 0, 0, 0, 0], np.int32)]


@pytest.fixture(scope="module")
def model_params():
    import jax
    from dalle_tpu.config import DalleConfig
    from dalle_tpu.models.dalle import init_dalle
    return init_dalle(DalleConfig(**CFG), jax.random.PRNGKey(0), batch=2)


def _ref(model_params, text, seed):
    import jax
    from dalle_tpu.models.dalle import DALLE
    model, params = model_params
    return np.asarray(model.apply(
        params, np.asarray(text[None]), jax.random.PRNGKey(seed),
        method=DALLE.generate_images_tokens)[0]).tolist()


def test_remote_replica_serves_bitwise_exact(model_params, tracer):
    from dalle_tpu.fleet import RemoteReplica, ReplicaServer
    from dalle_tpu.gateway import Replica
    from dalle_tpu.serve import DecodeEngine
    model, params = model_params
    rep = Replica(DecodeEngine(model, params, slots=2), maxsize=8).start()
    srv = ReplicaServer(rep).start()
    rem = RemoteReplica(srv.addr, heartbeat_s=0.1)
    try:
        # single submits: streamed rows concat == done == the sequential
        # reference, through the frame protocol
        for i, seed in enumerate((100, 101)):
            stream = rem.submit(TEXTS[i], seed=seed)
            rows, done = [], None
            for kind, payload in stream.events(timeout=60.0):
                if kind == "row":
                    rows.append(payload)
                elif kind == "done":
                    done = payload
            want = _ref(model_params, TEXTS[i], seed)
            assert done is not None and done.tokens == want
            assert [t for _r, chunk in rows for t in chunk] == want
        # a shared-prefix group: per-candidate streams bitwise equal the
        # independent per-seed generations
        group = rem.submit_group(TEXTS[0], seeds=[100, 105])
        done = {}
        for idx, kind, payload in group.events(timeout=60.0):
            if kind == "done":
                done[idx] = payload
        assert done[0].tokens == _ref(model_params, TEXTS[0], 100)
        assert done[1].tokens == _ref(model_params, TEXTS[0], 105)
        assert rem.health()["requests_served"] == 3
    finally:
        rem.close()
        srv.shutdown()
        rep.drain(timeout=30)


# ---------------------------------------------------------------------------
# AOT fingerprint refusal across processes (the satellite): a replica
# PROCESS handed a mismatched bundle must refuse loudly in its handshake
# and still serve correctly on the jit fallback
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_aot_fingerprint_refusal_across_processes(tmp_path):
    """Slow tier: ~15-20 s of subprocess jax import + jit-fallback compile
    (the tier-1 wall budget is tight — ROADMAP verify caps at 870 s). The
    same cross-process refusal path runs in every CI build via
    scripts/fleet_smoke.py's mismatched-bundle phase."""
    import sys
    from dalle_tpu.fleet import FleetManager
    # a bundle whose manifest can never match: refusal happens at the
    # fingerprint diff, before programs.pkl is ever opened, so a doctored
    # manifest exercises the exact cross-process path with zero parent-
    # side compiles
    bad_aot = tmp_path / "aot"
    bad_aot.mkdir()
    (bad_aot / "manifest.json").write_text(json.dumps(
        {"fingerprint": {"slots": 999}, "programs": []}))
    (bad_aot / "programs.pkl").write_bytes(b"never-read")
    mgr = FleetManager(
        [sys.executable, os.path.join(SCRIPTS, "serve_replica.py"),
         "--untrained", "--model_seed", "0", "--precision", "float32",
         "--slots", "1", "--steps_per_sync", "2",
         "--aot_dir", str(bad_aot), "--no_compile_cache",
         "--flight_dir", "off"],
        env={"JAX_PLATFORMS": "cpu"},
        log_dir=str(tmp_path / "logs"))
    try:
        rp = mgr.spawn()
        # the refusal is LOUD and structured: the handshake says the
        # bundle was rejected and names the first diverging key
        assert rp.handshake["aot_loaded"] is False
        assert "fingerprint mismatch" in rp.handshake["aot_refusal"]
        assert rp.remote.health()["aot_loaded"] is False
        # …and the replica still serves (jit fallback — cold, correct):
        # 8 tokens of the 16-token grid, structurally valid
        stream = rp.remote.submit(np.array([3, 4, 5, 0, 0, 0], np.int32),
                                  seed=123, max_tokens=8)
        done = None
        for kind, payload in stream.events(timeout=240.0,
                                           still_alive=lambda: True):
            if kind == "done":
                done = payload
        assert done is not None and len(done.tokens) == 8
        assert all(0 <= t < CFG["image_vocab_size"] for t in done.tokens)
    finally:
        mgr.shutdown()
