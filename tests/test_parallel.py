"""Mesh / backend / partitioning tests on the 8-device virtual CPU platform."""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from dalle_tpu.config import MeshConfig
from dalle_tpu.parallel import (build_mesh, shard_batch, local_batch_size,
                                set_backend_from_args, wrap_arg_parser, using_backend,
                                DummyBackend, JaxBackend, make_param_shardings,
                                spec_for, shard_params)


def test_eight_devices():
    assert jax.device_count() == 8


def test_build_mesh_shapes():
    mesh = build_mesh(MeshConfig(dp=2, fsdp=2, tp=2, sp=1))
    assert mesh.shape == {"dp": 2, "fsdp": 2, "sp": 1, "tp": 2}
    # dp auto-scales to absorb all devices
    mesh2 = build_mesh(MeshConfig(dp=1, fsdp=1, tp=2, sp=1))
    assert mesh2.shape["dp"] == 4


def test_shard_batch_and_local_batch(mesh8):
    batch = {"x": np.ones((16, 3)), "y": np.zeros((16,))}
    out = shard_batch(mesh8, batch)
    assert out["x"].sharding.spec == P(("dp", "fsdp"), None)
    assert local_batch_size(mesh8, 16) == 4


def test_backend_registry_and_cli():
    parser = argparse.ArgumentParser()
    wrap_arg_parser(parser)
    args = parser.parse_args(["--distributed_backend", "jax"])
    b = set_backend_from_args(args)
    assert isinstance(b, JaxBackend)
    assert using_backend("jax") and using_backend(JaxBackend)
    b.initialize(MeshConfig(dp=2, fsdp=2, tp=2))
    assert b.get_world_size() == 8
    assert b.is_root_worker()
    b.local_barrier()
    assert abs(b.average_all(jnp.array([2.0, 4.0])) - 3.0) < 1e-6


def test_dummy_backend_contract():
    args = argparse.Namespace(distributed_backend="dummy")
    b = set_backend_from_args(args)
    assert isinstance(b, DummyBackend)
    b.initialize()
    assert b.get_world_size() == 1
    assert b.is_root_worker() and b.is_local_root_worker()
    b.check_batch_size(1)
    p = b.distribute(params={"w": jnp.ones(2)})
    assert p["w"].shape == (2,)


def test_partition_rules_spec():
    # qkv kernel shards (fsdp, tp)
    s = spec_for("transformer/layers_0/attn/to_qkv/kernel", (512, 1536))
    assert s == P("fsdp", "tp")
    s = spec_for("dvae/encoder/conv_0/kernel", (4, 4, 3, 64))
    assert s == P(None, None, None, "fsdp")
    assert spec_for("norm/bias", (512,)) == P()


def test_spec_fallback_on_indivisible(mesh8):
    # dim 3 not divisible by tp=2 → replicated on that dim
    s = spec_for("x/attn/to_qkv/kernel", (3, 8), mesh=mesh8)
    assert s == P(None, "tp")


def test_shard_params_places_on_mesh(mesh8):
    params = {"attn": {"to_qkv": {"kernel": np.ones((8, 16), np.float32)}},
              "norm": {"bias": np.zeros((8,), np.float32)}}
    sharded = shard_params(mesh8, params)
    k = sharded["attn"]["to_qkv"]["kernel"]
    assert isinstance(k.sharding, NamedSharding)
    assert k.sharding.spec == P("fsdp", "tp")
    # sharded matmul still computes correctly
    x = shard_batch(mesh8, np.ones((8, 8), np.float32))
    y = jax.jit(lambda a, b: a @ b)(x, k)
    np.testing.assert_allclose(np.asarray(y), 8.0)
