"""Mesh / backend / partitioning tests on the 8-device virtual CPU platform."""

import argparse

import pytest

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from dalle_tpu.config import MeshConfig
from dalle_tpu.parallel import (build_mesh, shard_batch, local_batch_size,
                                set_backend_from_args, wrap_arg_parser, using_backend,
                                DummyBackend, JaxBackend, make_param_shardings,
                                spec_for, shard_params)


def test_eight_devices():
    assert jax.device_count() == 8


def test_build_mesh_shapes():
    mesh = build_mesh(MeshConfig(dp=2, fsdp=2, tp=2, sp=1))
    assert mesh.shape == {"dp": 2, "fsdp": 2, "sp": 1, "tp": 2}
    # dp auto-scales to absorb all devices
    mesh2 = build_mesh(MeshConfig(dp=1, fsdp=1, tp=2, sp=1))
    assert mesh2.shape["dp"] == 4


def test_shard_batch_and_local_batch(mesh8):
    batch = {"x": np.ones((16, 3)), "y": np.zeros((16,))}
    out = shard_batch(mesh8, batch)
    assert out["x"].sharding.spec == P(("dp", "fsdp"), None)
    assert local_batch_size(mesh8, 16) == 4


def test_backend_registry_and_cli():
    parser = argparse.ArgumentParser()
    wrap_arg_parser(parser)
    args = parser.parse_args(["--distributed_backend", "jax"])
    b = set_backend_from_args(args)
    assert isinstance(b, JaxBackend)
    assert using_backend("jax") and using_backend(JaxBackend)
    b.initialize(MeshConfig(dp=2, fsdp=2, tp=2))
    assert b.get_world_size() == 8
    assert b.is_root_worker()
    b.local_barrier()
    assert abs(b.average_all(jnp.array([2.0, 4.0])) - 3.0) < 1e-6


def test_dummy_backend_contract():
    args = argparse.Namespace(distributed_backend="dummy")
    b = set_backend_from_args(args)
    assert isinstance(b, DummyBackend)
    b.initialize()
    assert b.get_world_size() == 1
    assert b.is_root_worker() and b.is_local_root_worker()
    b.check_batch_size(1)
    p = b.distribute(params={"w": jnp.ones(2)})
    assert p["w"].shape == (2,)


def test_partition_rules_spec():
    # qkv kernel shards (fsdp, tp)
    s = spec_for("transformer/layers_0/attn/to_qkv/kernel", (512, 1536))
    assert s == P("fsdp", "tp")
    s = spec_for("dvae/encoder/conv_0/kernel", (4, 4, 3, 64))
    assert s == P(None, None, None, "fsdp")
    assert spec_for("norm/bias", (512,)) == P()


def test_spec_fallback_on_indivisible(mesh8):
    # dim 3 not divisible by tp=2 → replicated on that dim
    s = spec_for("x/attn/to_qkv/kernel", (3, 8), mesh=mesh8)
    assert s == P(None, "tp")


def test_shard_params_places_on_mesh(mesh8):
    params = {"attn": {"to_qkv": {"kernel": np.ones((8, 16), np.float32)}},
              "norm": {"bias": np.zeros((8,), np.float32)}}
    sharded = shard_params(mesh8, params)
    k = sharded["attn"]["to_qkv"]["kernel"]
    assert isinstance(k.sharding, NamedSharding)
    assert k.sharding.spec == P("fsdp", "tp")
    # sharded matmul still computes correctly
    x = shard_batch(mesh8, np.ones((8, 8), np.float32))
    y = jax.jit(lambda a, b: a @ b)(x, k)
    np.testing.assert_allclose(np.asarray(y), 8.0)


# --------------------------------------------------------------------------
# Real 2-process jax.distributed run over the loopback coordinator (the DCN
# path the dryrun can't cover: process_allgather, sync_global_devices,
# per-host shard split, rank/world queries across processes).
# --------------------------------------------------------------------------

_CHILD_CODE = """
import jax
jax.config.update('jax_platforms', 'cpu')
import argparse, sys
import numpy as np

pid, port, nproc = int(sys.argv[1]), sys.argv[2], int(sys.argv[3])
from dalle_tpu.parallel import backend as B

ap = argparse.ArgumentParser()
B.wrap_arg_parser(ap)
args = ap.parse_args([
    '--distributed_backend', 'jax',
    '--coordinator_address', f'127.0.0.1:{port}',
    '--num_processes', str(nproc), '--process_id', str(pid)])
b = B.set_backend_from_args(args).initialize()

assert jax.process_count() == nproc, jax.process_count()
assert b.get_world_size() == 2 * nproc, b.get_world_size()  # 2 devs/proc
assert b.get_rank() == pid * 2, (pid, b.get_rank())
assert b.is_root_worker() == (pid == 0)
assert b.is_local_root_worker()
b.local_barrier()                                           # sync_global_devices

avg = b.average_all(np.float32(pid))                        # process_allgather
assert abs(float(avg) - (nproc - 1) / 2) < 1e-6, avg

from dalle_tpu.data.webdataset import split_shards_per_host
shards = [f's{i}' for i in range(2 * nproc + 1)]
mine = split_shards_per_host(shards)
want = shards[pid::nproc]
assert mine == want, (mine, want)

b.local_barrier()
print(f'CHILD_OK {pid} rank={b.get_rank()}')
"""


def _run_dcn(tmp_path, nproc, child_code=None, devices_per_proc=2):
    """Spawn nproc coordinated children over a loopback coordinator. The
    env machinery lives in parallel/elastic.py (python_worker_env) — the
    graftmend harness promoted it out of this file so chaos_smoke and the
    DCN tests build children identically."""
    import os
    import subprocess
    import sys

    from dalle_tpu.parallel.elastic import free_port, python_worker_env

    port = free_port()
    script = tmp_path / "dcn_child.py"
    script.write_text(child_code or _CHILD_CODE)
    env = python_worker_env(
        devices_per_proc=devices_per_proc,
        repo_root=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

    procs = [subprocess.Popen(
        [sys.executable, str(script), str(i), str(port), str(nproc)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for i in range(nproc)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"child {i} failed:\n{out[-3000:]}"
        assert f"CHILD_OK {i}" in out


def test_two_process_dcn(tmp_path):
    """Real 2-process jax.distributed over a loopback coordinator."""
    _run_dcn(tmp_path, 2)


@pytest.mark.slow
def test_four_process_dcn(tmp_path):
    """4 hosts x 2 devices — multi-host beyond the pairwise case (rank
    arithmetic, shard split, allgather at world size 8)."""
    _run_dcn(tmp_path, 4)


# --------------------------------------------------------------------------
# Ring attention ACROSS processes: the sp mesh spans 2 procs x 4 devices, so
# half the ppermute hops cross the process (DCN) boundary — the single-
# process 8-device tests can't exercise that collective surface
# (VERDICT r2 next #7).
# --------------------------------------------------------------------------

_RING_SP_CHILD = """
import jax
jax.config.update('jax_platforms', 'cpu')
jax.config.update('jax_cpu_collectives_implementation', 'gloo')
import sys
import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

pid, port, nproc = int(sys.argv[1]), sys.argv[2], int(sys.argv[3])
jax.distributed.initialize(coordinator_address=f'127.0.0.1:{port}',
                           num_processes=nproc, process_id=pid)
assert jax.device_count() == 8 and jax.local_device_count() == 4

from dalle_tpu.parallel.ring_attention import ring_attention

mesh = Mesh(np.array(jax.devices()), ('sp',))
spec = P(None, None, 'sp', None)
sharding = NamedSharding(mesh, spec)

b, h, n, d = 2, 2, 256, 32
rng = np.random.RandomState(0)               # same on every process
qn, kn, vn = (rng.standard_normal((b, h, n, d)).astype(np.float32)
              for _ in range(3))

def put(a):
    return jax.make_array_from_callback(a.shape, sharding,
                                        lambda idx: a[idx])

q, k, v = put(qn), put(kn), put(vn)

# numpy oracle (f32 causal softmax attention)
s = np.einsum('bhid,bhjd->bhij', qn * d ** -0.5, kn)
s = np.where(np.tril(np.ones((n, n), bool)), s, -1e9)
p = np.exp(s - s.max(-1, keepdims=True))
ref = np.einsum('bhij,bhjd->bhid', p / p.sum(-1, keepdims=True), vn)

def check(out, what, tol=3e-5):
    shards = out.addressable_shards
    assert shards, what
    for sh in shards:
        np.testing.assert_allclose(np.asarray(sh.data), ref[sh.index],
                                   rtol=tol, atol=tol, err_msg=what)

for zigzag in (False, True):
    fn = jax.jit(lambda q, k, v, z=zigzag: ring_attention(
        q, k, v, mesh=mesh, causal=True, zigzag=z, kernel=False))
    check(fn(q, k, v), f'dense ring zigzag={zigzag}')

# kernel (pallas, interpret on CPU) ring: fwd numerics + the whole-ring
# custom_vjp backward, whose dk/dv ppermutes also cross the DCN boundary
kfn = jax.jit(lambda q, k, v: ring_attention(
    q, k, v, mesh=mesh, causal=True, zigzag=True, kernel=True,
    interpret=True))
check(kfn(q, k, v), 'kernel ring zigzag', tol=2e-4)

gfn = jax.jit(jax.grad(lambda q, k, v: jnp.sum(kfn(q, k, v) ** 2)))
gq = gfn(q, k, v)
for sh in gq.addressable_shards:
    assert np.isfinite(np.asarray(sh.data)).all(), 'kernel ring grad'

print(f'CHILD_OK {pid}')
"""


@pytest.mark.slow
def test_ring_attention_across_processes(tmp_path):
    """Ring attention over sp=8 spanning 2 processes (4 devices each):
    ppermute hops cross the process boundary in forward and in the kernel
    ring's backward; outputs verified shard-by-shard vs a numpy oracle."""
    _run_dcn(tmp_path, 2, child_code=_RING_SP_CHILD, devices_per_proc=4)
