"""Transformer stack tests: decode parity, token shift, layer sharing, variants."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_tpu.config import TransformerConfig
from dalle_tpu.models.transformer import (Transformer, layerscale_init_eps,
                                          shift_tokens_full)

# recompilation budget (conftest guard): ceiling = the module's cold
# full-run TOTAL (634 measured) + ~15% slack for cross-jax-version compile-
# count variance (CI installs unpinned jax); the total bounds any single
# test standalone in any order/subset — a mid-module per-test max would blow up under -k (a
# later parametrization run alone measured 356, riding no warm cache). A
# test exceeding this has introduced NEW compilation work — docs/LINT.md.
pytestmark = pytest.mark.recompile_budget(730)

FMAP = 4
TEXT = 8  # text_seq_len (excl bos)
SEQ = TEXT + FMAP * FMAP


def make(depth=2, **kw):
    cfg = TransformerConfig(seq_len=SEQ, dim=32, depth=depth, heads=2,
                            dim_head=16, image_fmap_size=FMAP, **kw)
    model = Transformer(cfg)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, SEQ + 1, 32))
    params = model.init(jax.random.PRNGKey(1), x)
    return model, params, x


def decode_all(model, params, x, prefill_len):
    n = x.shape[1]
    cache = model.apply(params, 2, n, method=Transformer.init_cache)
    y0, cache = model.apply(params, x[:, :prefill_len], cache,
                            method=Transformer.prefill)
    outs = [y0]
    for t in range(prefill_len, n):
        y, cache = model.apply(params, x[:, t:t + 1], cache, jnp.int32(t),
                               method=Transformer.decode_step)
        outs.append(y)
    return jnp.concatenate(outs, axis=1)


@pytest.mark.parametrize("attn_types,shift", [
    (("full",), False),
    (("full", "axial_row", "axial_col", "conv_like"), False),
    (("axial_row", "axial_col"), True),
    (("conv_like",), True),
])
def test_decode_matches_full(attn_types, shift):
    """Cache-vs-nocache equivalence — the reference's most delicate machinery
    (SURVEY §4 item 4)."""
    model, params, x = make(depth=len(attn_types), attn_types=attn_types,
                            shift_tokens=shift)
    full = model.apply(params, x)
    inc = decode_all(model, params, x, prefill_len=TEXT + 1)
    np.testing.assert_allclose(np.asarray(full), np.asarray(inc), atol=2e-5)


def test_int8_kv_cache_roundtrip():
    """Quantized storage: append → read_kv recovers values to ~amax/254 per
    row; scales live per (b, h, position)."""
    from dalle_tpu.ops.attention import KVCache
    rng = jax.random.PRNGKey(3)
    k_new, v_new = jax.random.normal(rng, (2, 2, 2, 6, 16))
    cache = KVCache.init(2, 2, 8, 16, dtype=jnp.int8)
    cache = cache.append(k_new, v_new, 2)
    ck, cv = cache.read_kv(dtype=jnp.float32)
    amax = float(jnp.max(jnp.abs(k_new)))
    np.testing.assert_allclose(np.asarray(ck[:, :, 2:8]), np.asarray(k_new),
                               atol=amax / 127)
    np.testing.assert_allclose(np.asarray(cv[:, :, 2:8]), np.asarray(v_new),
                               atol=float(jnp.max(jnp.abs(v_new))) / 127)
    assert (np.asarray(ck[:, :, :2]) == 0).all()     # untouched slots


@pytest.mark.parametrize("shift", [False, True])
def test_int8_kv_decode_close_to_f32(shift):
    """Cached decode with the int8 KV cache tracks the f32-cache decode
    within quantization noise (the int8 path halves cache-read bandwidth —
    the dominant cost of batched decode). shift=True also exercises f32
    activations against the bf16 token-shift ring buffers that ride along
    an int8 cache (writes cast to the buffer dtype)."""
    model, params, x = make(depth=2, shift_tokens=shift)
    full = decode_all(model, params, x, prefill_len=TEXT + 1)

    n = x.shape[1]
    cache = model.apply(params, 2, n, jnp.int8,
                        method=Transformer.init_cache)
    y0, cache = model.apply(params, x[:, :TEXT + 1], cache,
                            method=Transformer.prefill)
    outs = [y0]
    for t in range(TEXT + 1, n):
        y, cache = model.apply(params, x[:, t:t + 1], cache, jnp.int32(t),
                               method=Transformer.decode_step)
        outs.append(y)
    inc8 = jnp.concatenate(outs, axis=1)
    err = float(jnp.max(jnp.abs(inc8 - full)))
    # int8 KV noise ~1e-2 on N(0,1) activations; the bf16 shift buffers add
    # bf16 rounding (~8e-3 relative) on the shifted channels
    assert err < 0.08, err


def test_decode_matches_full_with_image_prime():
    """Prefill that already includes image tokens (priming path) must agree —
    this is where the reference's shift-cache prefill is subtly wrong."""
    model, params, x = make(depth=2, attn_types=("full", "axial_row"),
                            shift_tokens=True)
    full = model.apply(params, x)
    inc = decode_all(model, params, x, prefill_len=TEXT + 1 + 7)  # 7 primed img tokens
    np.testing.assert_allclose(np.asarray(full), np.asarray(inc), atol=2e-5)


def test_decode_matches_full_through_text_positions():
    """Cached decode that starts INSIDE the text span (the generate_texts path)
    must apply the text shift (½ channels from t−1), not the image-grid shift."""
    model, params, x = make(depth=2, attn_types=("full", "axial_row"),
                            shift_tokens=True)
    full = model.apply(params, x)
    inc = decode_all(model, params, x, prefill_len=3)  # bos + 2 text tokens
    np.testing.assert_allclose(np.asarray(full), np.asarray(inc), atol=2e-5)


def test_shift_tokens_full_semantics():
    b, d = 1, 8
    text_len, fmap = 3, 2
    n = text_len + fmap * fmap
    x = jnp.arange(b * n * d, dtype=jnp.float32).reshape(b, n, d)
    y = shift_tokens_full(x, text_len, fmap)
    # text position 0: first half zeros (shifted from nothing)
    np.testing.assert_array_equal(np.asarray(y[0, 0, :4]), 0.0)
    # text position 2: first half from position 1
    np.testing.assert_array_equal(np.asarray(y[0, 2, :4]), np.asarray(x[0, 1, :4]))
    # image (0,0) (global pos 3): top quarter zero, left quarter zero
    np.testing.assert_array_equal(np.asarray(y[0, 3, :4]), 0.0)
    # image (1,1) (global pos 6): top quarter from (0,1)=pos 4, left from (1,0)=pos 5
    np.testing.assert_array_equal(np.asarray(y[0, 6, :2]), np.asarray(x[0, 4, :2]))
    np.testing.assert_array_equal(np.asarray(y[0, 6, 2:4]), np.asarray(x[0, 5, 2:4]))
    # pass-through half untouched
    np.testing.assert_array_equal(np.asarray(y[0, 6, 4:]), np.asarray(x[0, 6, 4:]))


def test_layer_sharing_reduces_params():
    _, p_shared, _ = make(depth=4, shared_attn_ids=(0, 0, 1, 1),
                          shared_ff_ids=(0, 0, 0, 0))
    _, p_full, _ = make(depth=4)
    n_shared = sum(x.size for x in jax.tree.leaves(p_shared))
    n_full = sum(x.size for x in jax.tree.leaves(p_full))
    assert n_shared < n_full


def test_layer_sharing_type_mismatch_raises():
    with pytest.raises(ValueError, match="attn_types do not match"):
        make(depth=2, attn_types=("full", "axial_row"), shared_attn_ids=(0, 0))


def test_layerscale_init_thresholds():
    assert layerscale_init_eps(1) == 0.1
    assert layerscale_init_eps(18) == 0.1
    assert layerscale_init_eps(19) == 1e-5
    assert layerscale_init_eps(24) == 1e-5
    assert layerscale_init_eps(25) == 1e-6


def test_stable_and_sandwich_paths_run():
    model, params, x = make(depth=2, stable=True, sandwich_norm=True)
    out = model.apply(params, x)
    assert jnp.isfinite(out).all()


def test_sparse_variant_runs():
    model, params, x = make(depth=1, attn_types=("sparse",))
    out = model.apply(params, x)
    assert out.shape == x.shape


def test_sparse_layers_draw_distinct_patterns():
    """DeepSpeed VariableSparsityConfig parity: each 'sparse' layer gets its
    own random-block pattern (seed = sparse_mask_seed + layer index), not one
    shared table; deterministic types still share one mask per type."""
    # block 4 over seq 24 → a 6x6 block grid with 2 random blocks per row:
    # the default 128-block would cover this tiny seq with one all-True
    # block and no randomness to vary
    kw = dict(attn_types=("sparse", "axial_row"), sparse_block_size=4,
              sparse_num_random_blocks=2)
    model, params, x = make(depth=4, **kw)
    bound = model.bind(params)
    assert list(bound.mask_keys) == ["sparse_0", "axial_row",
                                     "sparse_2", "axial_row"]
    m0, m2 = bound.np_masks["sparse_0"], bound.np_masks["sparse_2"]
    assert (m0 != m2).any()
    # same base seed → reproducible patterns
    model2, params2, _ = make(depth=4, **kw)
    assert (model2.bind(params2).np_masks["sparse_0"] == m0).all()
