"""Serving gateway (dalle_tpu/gateway): admission control, SSE streaming of
committed grid rows, replica failover mid-stream, and the AOT cold-start
path — all loopback, no network deps beyond the stdlib HTTP stack.

The correctness bar rides PR 4's: tokens delivered through ANY gateway path
(SSE rows, blocking JSON, post-failover resumption, AOT executables) equal
single-request ``generate_images_tokens`` bitwise."""

import base64
import json
import os
import threading
import time

import numpy as np
import pytest

# ceiling = measured cold full-run total (309 with the shared module model:
# ~7 engine instances × refill/step(+row) pairs + the AOT export's —
# now four — .compile() calls + references) + ~15% cross-jax-version slack
# (the test_serve convention). Re-measured after graftloom (group streams,
# group failover, /v1/images validation, 4-program AOT bundle): well under
# the ceiling, which is kept at the PR7 calibration. A gateway change that
# recompiles per request or per replica restart would blow straight
# through this.
pytestmark = pytest.mark.recompile_budget(355)

CFG = dict(num_text_tokens=32, text_seq_len=6, dim=32, depth=2, heads=2,
           dim_head=16, image_size=16, image_vocab_size=24,
           image_fmap_size=4)

TEXTS = [np.array([3, 4, 5, 0, 0, 0], np.int32),
         np.array([7, 8, 0, 0, 0, 0], np.int32),
         np.array([9, 1, 2, 3, 0, 0], np.int32)]


@pytest.fixture(scope="module")
def model_params():
    import jax
    from dalle_tpu.config import DalleConfig
    from dalle_tpu.models.dalle import init_dalle
    return init_dalle(DalleConfig(**CFG), jax.random.PRNGKey(0), batch=2)


@pytest.fixture(scope="module")
def refs(model_params):
    """Single-request references, seed 100+i — the bitwise bar."""
    import jax
    from dalle_tpu.models.dalle import DALLE
    model, params = model_params
    return {i: np.asarray(model.apply(
        params, np.asarray(t[None]), jax.random.PRNGKey(100 + i),
        method=DALLE.generate_images_tokens)[0])
        for i, t in enumerate(TEXTS)}


def _engine(model_params, **kw):
    from dalle_tpu.serve import DecodeEngine
    model, params = model_params
    return DecodeEngine(model, params, slots=kw.pop("slots", 2), **kw)


# ---------------------------------------------------------------------------
# admission control (host-only)
# ---------------------------------------------------------------------------

def test_token_bucket_rate_and_burst():
    from dalle_tpu.gateway import TokenBucket
    b = TokenBucket(rate_per_s=2.0, burst=3.0)
    t0 = 1000.0
    assert all(b.try_acquire(1, now=t0) for _ in range(3))   # burst drains
    assert not b.try_acquire(1, now=t0)
    assert b.try_acquire(1, now=t0 + 0.5)                    # 0.5s → 1 token
    assert not b.try_acquire(1, now=t0 + 0.5)
    # refill caps at burst, never beyond
    assert all(b.try_acquire(1, now=t0 + 100.0) for _ in range(3))
    assert not b.try_acquire(1, now=t0 + 100.0)


def test_tenant_quotas_overrides_and_isolation():
    from dalle_tpu.gateway import TenantQuotas
    q = TenantQuotas(rate_per_s=100.0, burst=50.0,
                     overrides={"capped": (0.001, 1)})
    assert q.admit("capped")
    assert not q.admit("capped")           # burst 1 exhausted
    # another tenant's bucket is untouched by capped's exhaustion
    assert all(q.admit("open") for _ in range(10))


def test_admission_controller_quota_slo_and_accounting():
    from dalle_tpu.gateway import (AdmissionController, SloEstimator,
                                   TenantQuotas)
    ctl = AdmissionController(
        TenantQuotas(rate_per_s=0.001, burst=1,
                     overrides={"fast": (1000.0, 1000.0)}),
        SloEstimator())
    # unwarmed estimator must admit (and learn), never reject on SLO
    d = ctl.decide("fast", request_tokens=16, queued_tokens=1000,
                   deadline_s=0.001)
    assert d.admit
    ctl.slo.observe(tokens=100, seconds=1.0)        # 100 tok/s
    d = ctl.decide("fast", request_tokens=16, queued_tokens=984,
                   deadline_s=1.0)                  # predicted 10s > 1s
    assert not d.admit and d.reason == "slo"
    assert d.predicted_completion_s == pytest.approx(10.0)
    assert d.retry_after_s == pytest.approx(9.0)
    d = ctl.decide("fast", request_tokens=16, queued_tokens=0,
                   deadline_s=1.0)                  # 0.16s < 1s
    assert d.admit
    # quota tenant: first passes (burst 1), second rejected with the reason
    assert ctl.decide("slow", request_tokens=16, queued_tokens=0).admit
    d = ctl.decide("slow", request_tokens=16, queued_tokens=0)
    assert not d.admit and d.reason == "quota" and d.retry_after_s > 0
    assert ctl.rejected == {"fast": 1, "slow": 1}
    # out-of-band rejects (the gateway's queue_full path) land in the same
    # per-tenant book via the public reject()
    d = ctl.reject("slow", "queue_full")
    assert not d.admit and ctl.rejected["slow"] == 2


def test_slo_estimator_fleet_parallelism():
    """Completions observe PER-REQUEST rate; with B slots the backlog
    drains ~B× faster, so the predictor scales by the configured fleet
    parallelism — otherwise it overestimates waits by ~B and sheds
    traffic the fleet would serve comfortably."""
    from dalle_tpu.gateway import SloEstimator
    solo = SloEstimator()
    fleet = SloEstimator(parallelism=4)
    for est in (solo, fleet):
        est.observe(tokens=100, seconds=1.0)
    assert solo.predict_completion_s(900, 100) == pytest.approx(10.0)
    assert fleet.predict_completion_s(900, 100) == pytest.approx(2.5)


# ---------------------------------------------------------------------------
# SSE framing (host-only)
# ---------------------------------------------------------------------------

def test_sse_event_roundtrip():
    import io
    from dalle_tpu.gateway import iter_sse, sse_event
    frames = (sse_event("row", {"request_id": 1, "row": 0,
                                "tokens": [5, 6, 7]})
              + b": keepalive comment\n\n"
              + sse_event("done", {"request_id": 1, "tokens": [5, 6, 7]}))
    parsed = list(iter_sse(io.BytesIO(frames)))
    assert parsed == [("row", {"request_id": 1, "row": 0,
                               "tokens": [5, 6, 7]}),
                      ("done", {"request_id": 1, "tokens": [5, 6, 7]})]


def test_row_pixel_decoder_bands():
    """Committed-prefix preview: the decoder is handed rows 0..r and crops
    row r's pixel band — shapes and dtype pinned with a stub vae (the real
    dVAE path is exercised by the gateway smoke)."""
    from dalle_tpu.gateway import RowPixelDecoder

    class StubVae:
        def decode(self, ids):           # (1, 16) ids → (1, 8, 8, 3) image
            assert ids.shape == (1, 16)
            # encode how many tokens were committed into the pixel value
            frac = float((ids != 0).sum()) / 16.0
            return np.full((1, 8, 8, 3), frac, np.float32)

    dec = RowPixelDecoder(StubVae(), image_fmap_size=4)
    out0 = dec.row_event(7, 0, [1, 2, 3, 4])
    band0 = np.frombuffer(base64.b64decode(out0["pixels_b64"]),
                          np.uint8).reshape(out0["pixels_shape"])
    assert band0.shape == (2, 8, 3) and band0.dtype == np.uint8
    out1 = dec.row_event(7, 1, [5, 6, 7, 8])
    band1 = np.frombuffer(base64.b64decode(out1["pixels_b64"]),
                          np.uint8).reshape(out1["pixels_shape"])
    # second row's decode saw 8 committed tokens, first saw 4
    assert band1[0, 0, 0] > band0[0, 0, 0]
    dec.finish(7)
    assert 7 not in dec._rows


def test_result_stream_timeout_is_replica_failure():
    from dalle_tpu.gateway import ResultStream
    s = ResultStream(request=None)
    events = list(s.events(timeout=0.05))
    assert events == [("replica_failed", "event timeout")]


# ---------------------------------------------------------------------------
# engine streaming + replica fleet (jax)
# ---------------------------------------------------------------------------

def test_engine_on_rows_streams_committed_rows(model_params, refs):
    """on_rows fires per committed fmap row, in order, and the concatenated
    rows equal the final tokens — incl. the trailing partial row of a
    max_tokens request."""
    from dalle_tpu.serve import RequestQueue
    model, params = model_params
    q = RequestQueue()
    q.submit(TEXTS[0], seed=100, request_id=0)
    q.submit(TEXTS[1], seed=101, request_id=1, max_tokens=6)
    q.close()
    rows = {0: [], 1: []}
    eng = _engine(model_params)
    done = eng.run(q, on_rows=lambda req, row, toks:
                   rows[req.request_id].append((row, list(toks))))
    assert sorted(c.request_id for c in done) == [0, 1]
    fmap = CFG["image_fmap_size"]
    assert [r for r, _ in rows[0]] == list(range(fmap))
    assert all(len(t) == fmap for _, t in rows[0])
    assert [t for _, ts in rows[0] for t in ts] == refs[0].tolist()
    # 6 tokens = one full row + a 2-token trailing partial row
    assert [(r, len(t)) for r, t in rows[1]] == [(0, 4), (1, 2)]
    assert [t for _, ts in rows[1] for t in ts] == refs[1][:6].tolist()


def test_replica_failover_midstream_exact(model_params, refs):
    """Replica A dies after 2 streamed rows; the router resubmits to B and
    the spliced stream delivers every row exactly once — final tokens
    bitwise-equal the single-request reference, B serving."""
    from dalle_tpu.gateway import Replica, ReplicaRouter
    ra = Replica(_engine(model_params), replica_id="ga").start()
    rb = Replica(_engine(model_params), replica_id="gb").start()
    router = ReplicaRouter([ra, rb])
    ra.fail_after_rows(2)
    routed = router.submit(TEXTS[2], 102)
    assert routed.replica_id == "ga"        # both idle → list order
    rows, done = [], None
    for kind, payload in routed.events(timeout=60):
        if kind == "row":
            rows.append(payload)
        elif kind == "done":
            done = payload
    assert [r["row"] for r in rows] == list(range(CFG["image_fmap_size"]))
    assert [t for r in rows for t in r["tokens"]] == refs[2].tolist()
    assert done["tokens"] == refs[2].tolist()
    assert done["replica"] == "gb" and done["failovers"] == 1
    assert not ra.healthy and rb.healthy
    router.drain(timeout=30)


def test_trace_id_survives_failover_and_bundles(model_params, refs,
                                                tmp_path):
    """graftscope: a routed request keeps ONE trace_id across the victim
    replica's admission, the failover resubmission and the standby's
    admission — every span it touched is tagged with it — and the failover
    leaves a flight-recorder bundle holding the replica_failed + failover
    lifecycle events and the dying worker's last decode-row spans."""
    from dalle_tpu import obs
    from dalle_tpu.gateway import Replica, ReplicaRouter
    obs.disable()
    tr = obs.configure()
    obs.configure_recorder(str(tmp_path), min_dump_interval_s=0.0)
    try:
        ra = Replica(_engine(model_params), replica_id="fa").start()
        rb = Replica(_engine(model_params), replica_id="fb").start()
        router = ReplicaRouter([ra, rb])
        ra.fail_after_rows(2)
        routed = router.submit(TEXTS[2], 102)
        tid = routed.trace_id
        assert tid                        # minted at submit for direct callers
        done = None
        for kind, payload in routed.events(timeout=60):
            if kind == "done":
                done = payload
        assert done["tokens"] == refs[2].tolist() and done["failovers"] == 1
        spans = [s for s in tr.snapshot_spans()
                 if (s[5] or {}).get("trace_id") == tid]
        qwaits = [s for s in spans if s[0] == "serve/request_queue_wait"]
        assert len(qwaits) == 2           # one identity, two admissions
        assert {s[0] for s in spans} >= {"serve/prefill", "serve/decode_row"}
        assert len({s[3] for s in spans}) >= 2    # victim + standby threads

        bundles = sorted(p for p in os.listdir(tmp_path)
                         if p.startswith("postmortem_failover"))
        assert bundles
        pm = json.load(open(tmp_path / bundles[-1] / "postmortem.json"))
        kinds = [e["kind"] for e in pm["events"]]
        assert "replica_failed" in kinds and "failover" in kinds
        fo = next(e for e in pm["events"] if e["kind"] == "failover")
        assert fo["trace_id"] == tid and fo["from_replica"] == "fa"
        trace = json.load(open(tmp_path / bundles[-1] / "trace.json"))
        dying_rows = [e for e in trace["traceEvents"]
                      if (e.get("args") or {}).get("trace_id") == tid
                      and e["name"] == "serve/decode_row"]
        assert dying_rows                 # the victim's last committed rows
        router.drain(timeout=30)
    finally:
        obs.disable()
        obs.disable_recorder()


def test_replica_deadline_shed_event(model_params):
    """PriorityDeadlinePolicy sheds an already-expired request at take time
    and its stream terminates with the shed event (gateway → 504), while
    the live request completes."""
    from dalle_tpu.gateway import Replica
    from dalle_tpu.serve import PriorityDeadlinePolicy
    rep = Replica(_engine(model_params),    # slots=2: shares programs with
                  policy=PriorityDeadlinePolicy()).start()   # the module's
    live = [rep.submit(TEXTS[i], 100 + i) for i in range(2)]  # other engines
    dead = rep.submit(TEXTS[2], 102,
                      deadline_at=time.perf_counter() - 1.0)
    kinds = [k for k, _ in dead.events(timeout=60)]
    assert kinds == ["shed"]
    for s in live:
        assert [k for k, _ in s.events(timeout=60)][-1] == "done"
    assert rep.queue.shed_total == 1
    rep.drain(timeout=30)


def test_gateway_loopback_stream_quota_health(model_params, refs):
    """One real socket round-trip: SSE stream bit-exact, second request of
    a burst-1 tenant → 429 + Retry-After, /healthz and /metrics live, 404
    for unknown paths, drain flips to 503."""
    import http.client
    from dalle_tpu import obs
    from dalle_tpu.gateway import (AdmissionController, Gateway, Replica,
                                   ReplicaRouter, TenantQuotas, iter_sse)
    obs.configure()
    try:
        rep = Replica(_engine(model_params), maxsize=8).start()
        gw = Gateway(ReplicaRouter([rep]), AdmissionController(TenantQuotas(
            rate_per_s=100.0, burst=100.0,
            overrides={"capped": (0.001, 1)}))).start()
        host, port = gw.httpd.server_address[:2]

        def post(payload):
            conn = http.client.HTTPConnection(host, port, timeout=60)
            conn.request("POST", "/v1/generate", json.dumps(payload))
            return conn, conn.getresponse()

        conn, resp = post({"text": TEXTS[0].tolist(), "seed": 100,
                           "stream": True})
        assert resp.status == 200
        assert resp.getheader("Content-Type") == "text/event-stream"
        tid = resp.getheader("X-Request-Id")
        assert tid                        # the door-minted graftscope id
        events = list(iter_sse(resp))
        conn.close()
        rows = [d for e, d in events if e == "row"]
        done = [d for e, d in events if e == "done"]
        assert [t for r in rows for t in r["tokens"]] == refs[0].tolist()
        assert done and done[0]["tokens"] == refs[0].tolist()
        assert all(d.get("trace_id") == tid for _, d in events)

        conn, resp = post({"text": TEXTS[1].tolist(), "seed": 101,
                           "tenant": "capped"})
        assert resp.status == 200
        assert json.loads(resp.read())["tokens"] == refs[1].tolist()
        conn.close()
        # completions warm the admission estimator AT THE DOOR (graftward
        # satellite: one feed point for every topology — no per-replica
        # on_served wiring, and remote fleets warm it identically)
        assert (gw.admission.slo.tokens_per_s or 0) > 0
        conn, resp = post({"text": TEXTS[2].tolist(), "seed": 102,
                           "tenant": "capped"})
        body = json.loads(resp.read())
        assert resp.status == 429 and body["error"] == "quota"
        assert float(resp.getheader("Retry-After")) > 0
        assert resp.getheader("X-Request-Id")  # errors join the timeline too
        conn.close()

        conn = http.client.HTTPConnection(host, port, timeout=10)
        conn.request("GET", "/healthz")
        health = json.loads(conn.getresponse().read())
        assert health["status"] == "ok"
        assert health["replicas"][0]["healthy"]
        conn.close()
        # the streamed POST's handler may still be inside its exit
        # bookkeeping when the client saw SSE EOF (HTTP/1.0 close races the
        # server-side finally), so poll the scrape briefly for inflight=0
        deadline = time.time() + 5.0
        while True:
            conn = http.client.HTTPConnection(host, port, timeout=10)
            conn.request("GET", "/metrics")
            metrics = conn.getresponse().read().decode()
            conn.close()
            if "dalle_gateway_inflight 0" in metrics or time.time() > deadline:
                break
            time.sleep(0.05)
        assert "dalle_gateway_rejected_total" in metrics
        assert "dalle_gateway_inflight 0" in metrics
        conn = http.client.HTTPConnection(host, port, timeout=10)
        conn.request("GET", "/nope")
        assert conn.getresponse().status == 404
        conn.close()

        gw.shutdown(drain=True, timeout=30)
        assert not rep.healthy          # worker exited at drain
    finally:
        obs.disable()


# ---------------------------------------------------------------------------
# shared-prefix candidate groups (graftloom /v1/images plumbing)
# ---------------------------------------------------------------------------

def test_replica_group_stream_merged_and_exact(model_params):
    """submit_group: N candidates enqueue atomically with consecutive ids
    (one engine admission → ONE shared prefill) and the merged GroupStream
    yields per-candidate rows + dones whose tokens are bitwise the
    per-seed single-request references."""
    import jax
    from dalle_tpu.gateway import Replica
    from dalle_tpu.models.dalle import DALLE
    model, params = model_params
    g_refs = [np.asarray(model.apply(
        params, np.asarray(TEXTS[0][None]), jax.random.PRNGKey(s),
        method=DALLE.generate_images_tokens)[0]) for s in (200, 201)]
    rep = Replica(_engine(model_params), replica_id="grp").start()
    group = rep.submit_group(TEXTS[0], [200, 201])
    assert group.request_ids == [0, 1]        # consecutive → one admission
    rows = {0: [], 1: []}
    done = {}
    for idx, kind, payload in group.events(timeout=60):
        if kind == "row":
            rows[idx].extend(payload[1])
        elif kind == "done":
            done[idx] = payload
    assert sorted(done) == [0, 1]
    for i in (0, 1):
        assert rows[i] == g_refs[i].tolist()
        np.testing.assert_array_equal(done[i].tokens, g_refs[i])
    assert rep.engine.stats.shared_refills == 1
    rep.drain(timeout=30)


def test_replica_group_capacity_precheck_atomic(model_params):
    """A group that would only partially fit raises QueueFull with NOTHING
    enqueued — half an admitted group would decode candidates nobody is
    waiting for."""
    from dalle_tpu.gateway import Replica
    from dalle_tpu.serve import QueueFull
    rep = Replica(_engine(model_params), replica_id="cap",
                  maxsize=1).start()
    with pytest.raises(QueueFull):
        rep.submit_group(TEXTS[0], [1, 2])
    assert rep.queue.qsize() == 0
    assert rep._streams == {}
    rep.drain(timeout=30)


def test_group_failover_midstream_resubmits_whole_group(model_params):
    """Replica death mid-group: the router resubmits the WHOLE group —
    same text, same per-candidate seeds — so every candidate regenerates
    bit-identically on the standby; per-candidate row high-water marks
    keep each row delivered exactly once."""
    import jax
    from dalle_tpu.gateway import Replica, ReplicaRouter
    from dalle_tpu.models.dalle import DALLE
    model, params = model_params
    g_refs = [np.asarray(model.apply(
        params, np.asarray(TEXTS[1][None]), jax.random.PRNGKey(s),
        method=DALLE.generate_images_tokens)[0]) for s in (300, 301)]
    ra = Replica(_engine(model_params), replica_id="ga2").start()
    rb = Replica(_engine(model_params), replica_id="gb2").start()
    router = ReplicaRouter([ra, rb])
    ra.fail_after_rows(3)
    routed = router.submit_images(TEXTS[1], [300, 301])
    rows = {0: [], 1: []}
    done = None
    for kind, payload in routed.events(timeout=60):
        if kind == "row":
            rows[payload["candidate"]].append(payload["row"])
        elif kind == "done":
            done = payload
    fmap = CFG["image_fmap_size"]
    for i in (0, 1):                          # every row exactly once
        assert rows[i] == list(range(fmap))
    assert done is not None and done["failovers"] == 1
    assert done["replica"] == "gb2"
    assert done["candidates"] == [r.tolist() for r in g_refs]
    assert not ra.healthy and rb.healthy
    router.drain(timeout=30)


def test_gateway_images_validation_rejects_before_admission(model_params):
    """/v1/images input bounds come back 400 at the HTTP door — never an
    engine-thread kill that fleet failover would replay. No request below
    reaches a slot, so this costs no decode."""
    import http.client
    from dalle_tpu.gateway import AdmissionController, Gateway, Replica, \
        ReplicaRouter
    rep = Replica(_engine(model_params), maxsize=8).start()
    gw = Gateway(ReplicaRouter([rep]), AdmissionController()).start()
    host, port = gw.httpd.server_address[:2]
    assert gw.max_candidates == 2             # capped by the slot budget

    def post(payload):
        conn = http.client.HTTPConnection(host, port, timeout=30)
        conn.request("POST", "/v1/images", json.dumps(payload))
        resp = conn.getresponse()
        body = json.loads(resp.read())
        conn.close()
        return resp.status, body

    base = {"text": TEXTS[0].tolist(), "seed": 1}
    for bad in ({**base, "n_candidates": 3},          # > slot budget
                {**base, "n_candidates": 0},
                {**base, "n_candidates": 2, "top_k": 3},
                {**base, "top_k": 0},
                {**base, "n_candidates": 2, "seed": 2**31 - 1},  # seed wrap
                {**base, "text": [TEXTS[0].tolist()]},           # 2-D text
                {**base, "max_tokens": 0},
                {"seed": 1}):                                    # no text
        status, body = post(bad)
        assert status == 400 and body["error"] == "bad_request", bad
    gw.shutdown(drain=True, timeout=30)


# ---------------------------------------------------------------------------
# AOT cold start (jax)
# ---------------------------------------------------------------------------

def test_aot_roundtrip_equality_and_fingerprint(model_params, refs,
                                                tmp_path):
    """Serialized executables round-trip: an AOT-loaded engine's tokens are
    bitwise-equal the jit-traced execution (and the reference); a
    config-mismatched engine refuses the bundle (False, or raises under
    strict). The zero-backend-compile cold-start assertion lives in
    scripts/gateway_smoke.py, which builds the cold engine over a fresh
    model instance so engine-level program sharing can't make the zero
    vacuous."""
    from dalle_tpu.gateway import (engine_fingerprint, load_engine_aot,
                                   save_engine_aot)
    from dalle_tpu.serve import RequestQueue
    aot_dir = str(tmp_path / "aot")
    exporter = _engine(model_params)
    manifest = save_engine_aot(exporter, aot_dir)
    assert manifest["fingerprint"] == engine_fingerprint(exporter)
    assert set(manifest["payload_bytes"]) == {"step", "refill",
                                              "refill_row", "refill_shared"}

    # jit-traced execution of the SAME programs, for the equality bar
    q = RequestQueue()
    for i, t in enumerate(TEXTS):
        q.submit(t, seed=100 + i, request_id=i)
    q.close()
    jit_done = {c.request_id: c.tokens for c in exporter.run(q)}

    cold = _engine(model_params)
    assert load_engine_aot(cold, aot_dir, strict=True)
    assert cold.aot_loaded
    q = RequestQueue()
    for i, t in enumerate(TEXTS):
        q.submit(t, seed=100 + i, request_id=i)
    q.close()
    cold_done = {c.request_id: c.tokens for c in cold.run(q)}
    for i in range(len(TEXTS)):
        np.testing.assert_array_equal(cold_done[i], jit_done[i])
        np.testing.assert_array_equal(cold_done[i], refs[i])

    # an AOT-loaded engine can't be re-exported (nothing left to lower)
    with pytest.raises(ValueError, match="AOT-loaded"):
        save_engine_aot(cold, str(tmp_path / "aot2"))

    # mismatched config (different slot count → different programs)
    other = _engine(model_params, slots=3)
    assert load_engine_aot(other, aot_dir) is False
    assert not other.aot_loaded
    with pytest.raises(ValueError, match="fingerprint mismatch on 'slots'"):
        load_engine_aot(other, aot_dir, strict=True)
