"""Speculative multi-token decode (DALLE.generate_images_tokens_speculative):
the acceptance machinery must be EXACT — gamma=0 (pure sequential under the
same per-(step,row) key discipline) and any gamma>0 produce identical token
sequences for any draft quality, trained or not. Reference bar: the strictly
sequential generate_images loop (dalle_pytorch/dalle_pytorch.py:523-546)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_tpu.config import DalleConfig
from dalle_tpu.models.dalle import DALLE, init_dalle

# recompilation budget (conftest guard): ceiling = the module's cold
# full-run TOTAL (530 measured after the PR4 windowed-kernel/int8 decode
# growth, with the module-scoped _model cache sharing one init across all
# tests) + ~15% slack for cross-jax-version compile-count variance; the
# total bounds any single test standalone in any order/subset. A
# speculative-decode change that recompiles per gamma/row would still blow
# straight through this — that is the point.
pytestmark = pytest.mark.recompile_budget(610)

CFG = dict(num_text_tokens=32, text_seq_len=6, dim=32, depth=2, heads=2,
           dim_head=16, image_size=16, image_vocab_size=24, image_fmap_size=4)


@functools.lru_cache(maxsize=None)
def _model(**kw):
    # module-scoped sharing: every test reads the same (model, params) —
    # jax arrays are immutable, and the one test that trains rebinds params
    # locally. Re-initializing per test re-ran the init program and the
    # first decode compiles for each config (~5 s each on this box).
    cfg = DalleConfig(**{**CFG, **kw})
    return init_dalle(cfg, jax.random.PRNGKey(0), batch=2)


def _gen(model, params, text, key, **kw):
    return np.asarray(model.apply(
        params, text, key,
        method=DALLE.generate_images_tokens_speculative, **kw))


# the repeat-draft variant of the same rejection-unbiasedness invariant
# rides the slow tier (~5s); row (the default draft) stays fast
@pytest.mark.parametrize(
    "draft", ["row", pytest.param("repeat", marks=pytest.mark.slow)])
def test_gamma_matches_sequential_untrained(draft):
    """Untrained model: acceptance ≈ chance, yet outputs must be identical —
    rejection must never bias the sampled sequence."""
    model, params = _model()
    text = jnp.asarray([[3, 4, 5, 0, 0, 0], [7, 8, 0, 0, 0, 0]], jnp.int32)
    key = jax.random.PRNGKey(42)
    seq = _gen(model, params, text, key, gamma=0)
    for gamma in (1, 3):
        spec = _gen(model, params, text, key, gamma=gamma, draft=draft)
        np.testing.assert_array_equal(spec, seq)
    assert seq.shape == (2, 16) and (seq >= 0).all() and (seq < 24).all()


def test_gamma_matches_sequential_axial_posemb():
    """rotary off → axial positional embedding path through the window."""
    model, params = _model(rotary_emb=False)
    text = jnp.asarray([[3, 4, 5, 0, 0, 0], [7, 8, 0, 0, 0, 0]], jnp.int32)
    key = jax.random.PRNGKey(7)
    seq = _gen(model, params, text, key, gamma=0)
    spec = _gen(model, params, text, key, gamma=2)
    np.testing.assert_array_equal(spec, seq)


def test_int8_cache_matches_and_stats():
    """int8 KV storage through append_rows + window attend; stats plumbed."""
    model, params = _model()
    text = jnp.asarray([[3, 4, 5, 0, 0, 0], [7, 8, 0, 0, 0, 0]], jnp.int32)
    key = jax.random.PRNGKey(3)
    seq = _gen(model, params, text, key, gamma=0, cache_dtype=jnp.int8)
    out, rounds, committed = model.apply(
        params, text, key, gamma=3, cache_dtype=jnp.int8, return_stats=True,
        method=DALLE.generate_images_tokens_speculative)
    np.testing.assert_array_equal(np.asarray(out), seq)
    assert int(committed) == 2 * 16
    # worst case one token per row per round
    assert 1 <= int(rounds) <= 16


def test_wrapper_speculative_route():
    """DalleWithVae.generate_images(speculative=γ) routes through the
    draft-and-verify sampler end-to-end (ids → VAE decode), and rejects
    CFG."""
    from dalle_tpu.config import DVAEConfig
    from dalle_tpu.models.dvae import DiscreteVAE
    from dalle_tpu.models.wrapper import DalleWithVae, DiscreteVAEAdapter

    model, params = _model()
    vcfg = DVAEConfig(image_size=16, num_tokens=24, codebook_dim=16,
                      num_layers=2, hidden_dim=16, num_resnet_blocks=1)
    vae_model = DiscreteVAE(vcfg)
    vparams = vae_model.init(jax.random.PRNGKey(0),
                             jnp.zeros((1, 16, 16, 3)))
    vae = DiscreteVAEAdapter(vae_model, vparams)
    dv = DalleWithVae(model, params, vae)
    text = jnp.asarray([[3, 4, 5, 0, 0, 0], [7, 8, 0, 0, 0, 0]], jnp.int32)
    out = dv.generate_images(text, jax.random.PRNGKey(2), speculative=2,
                             precision="bf16_int8kv")
    assert out.shape == (2, 16, 16, 3) and bool(jnp.isfinite(out).all())
    with pytest.raises(ValueError):
        dv.generate_images(text, jax.random.PRNGKey(2), speculative=2,
                           cond_scale=2.0)


def test_trained_model_accepts_drafts():
    """A model overfit to a constant image accepts 'repeat' drafts at a high
    rate — rounds must drop well below the sequential count."""
    import optax
    model, params = _model()
    text = jnp.asarray([[3, 4, 5, 0, 0, 0], [3, 4, 5, 0, 0, 0]], jnp.int32)
    img = jnp.full((2, 16), 5, jnp.int32)     # constant image: repeat-friendly
    tx = optax.adam(2e-3)
    state = tx.init(params)

    @jax.jit
    def step(params, state):
        def loss_fn(p):
            loss, _ = model.apply(p, text, img, return_loss=True)
            return loss
        loss, grads = jax.value_and_grad(loss_fn)(params)
        upd, state2 = tx.update(grads, state)
        return optax.apply_updates(params, upd), state2, loss

    for _ in range(150):
        params, state, loss = step(params, state)
    key = jax.random.PRNGKey(1)
    seq = _gen(model, params, text, key, gamma=0, temperature=0.2)
    out, rounds, committed = model.apply(
        params, text, key, gamma=3, draft="repeat", temperature=0.2,
        return_stats=True, method=DALLE.generate_images_tokens_speculative)
    np.testing.assert_array_equal(np.asarray(out), seq)
    assert (np.asarray(out) == 5).mean() > 0.9, "model failed to overfit"
    # 16 tokens at ≥2 committed/round on average → ≤ 8-ish rounds; allow slack
    assert int(rounds) <= 10, f"no speculation win on overfit model: {rounds}"
