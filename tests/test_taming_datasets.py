"""taming dataset family: item contracts over synthetic local file trees."""

import json

import numpy as np
import pytest
from PIL import Image

from dalle_tpu.data.taming_datasets import (ADE20k, CocoCaptions, CustomTest,
                                            CustomTrain, FacesHQ,
                                            ImageNetTrain, NumpyPaths)


def _png(path, size=(20, 14), color=(120, 30, 30)):
    path.parent.mkdir(parents=True, exist_ok=True)
    Image.new("RGB", size, color).save(path)


class TestCustom:
    def test_file_list(self, tmp_path):
        for i in range(3):
            _png(tmp_path / f"im{i}.png")
        lst = tmp_path / "train.txt"
        lst.write_text("\n".join(str(tmp_path / f"im{i}.png") for i in range(3)))
        ds = CustomTrain(size=8, training_images_list_file=str(lst))
        assert len(ds) == 3
        item = ds[0]
        assert item["image"].shape == (8, 8, 3)
        assert -1.0 <= item["image"].min() and item["image"].max() <= 1.0
        assert len(CustomTest(8, str(lst))) == 3


def test_numpy_paths(tmp_path):
    arr = (np.random.RandomState(0).rand(12, 12, 3) * 255).astype(np.uint8)
    np.save(tmp_path / "a.npy", arr)
    ds = NumpyPaths([str(tmp_path / "a.npy")], size=8)
    item = ds[0]
    assert item["image"].shape == (8, 8, 3)
    assert item["image"].min() >= -1.0 and item["image"].max() <= 1.0


def test_imagenet_synsets(tmp_path):
    for s, n in (("n01440764", 2), ("n01443537", 1)):
        for i in range(n):
            _png(tmp_path / s / f"{s}_{i}.JPEG".replace("JPEG", "jpeg"))
    ds = ImageNetTrain(str(tmp_path), size=8,
                       synset_to_human={"n01440764": "tench"})
    assert len(ds) == 3
    item = ds[0]
    assert item["class_label"] == 0 and item["human_label"] == "tench"
    assert item["image"].shape == (8, 8, 3)


def test_coco_captions(tmp_path):
    imgs = tmp_path / "images"
    _png(imgs / "0001.jpg")
    _png(imgs / "0002.jpg")
    ann = {"images": [{"id": 1, "file_name": "0001.jpg"},
                      {"id": 2, "file_name": "0002.jpg"}],
           "annotations": [{"image_id": 1, "caption": "a red thing"},
                           {"image_id": 1, "caption": "another view"},
                           {"image_id": 2, "caption": "a second image"}]}
    (tmp_path / "captions.json").write_text(json.dumps(ann))
    ds = CocoCaptions(str(imgs), str(tmp_path / "captions.json"), size=8)
    assert len(ds) == 2
    item = ds[0]
    assert item["caption"] in item["all_captions"]
    assert len(ds[0]["all_captions"]) == 2


def test_ade20k_segmentation(tmp_path):
    _png(tmp_path / "img" / "scene1.jpg")
    mask = Image.fromarray(np.full((10, 10), 7, np.uint8))
    (tmp_path / "ann").mkdir()
    mask.save(tmp_path / "ann" / "scene1.png")
    ds = ADE20k(str(tmp_path / "img"), str(tmp_path / "ann"), size=8)
    item = ds[0]
    assert item["segmentation"].shape == (8, 8, 151)
    assert (item["mask"] == 7).all()
    assert item["segmentation"][0, 0, 7] == 1.0


def test_faceshq_mix(tmp_path):
    for i in range(2):
        _png(tmp_path / f"celeb{i}.png")
        _png(tmp_path / f"ffhq{i}.png")
    cl = tmp_path / "celeba.txt"
    fl = tmp_path / "ffhq.txt"
    cl.write_text("\n".join(str(tmp_path / f"celeb{i}.png") for i in range(2)))
    fl.write_text("\n".join(str(tmp_path / f"ffhq{i}.png") for i in range(2)))
    ds = FacesHQ(str(cl), str(fl), size=8)
    assert len(ds) == 4
    assert ds[0]["class"] == 0 and ds[3]["class"] == 1


def test_numpy_paths_dtype_conventions(tmp_path):
    rng = np.random.RandomState(0)
    base = rng.rand(16, 16, 3)
    stores = {
        "u8": (base * 255).astype(np.uint8),
        "u16": (base * 65535).astype(np.uint16),
        "i64": (base * 255).astype(np.int64),    # numpy default int, 0-255
        "f01": base.astype(np.float32),
        "f255": (base * 255).astype(np.float32),
        "f_overshoot": np.clip(base * 1.0000001, 0, 1.0000001).astype(np.float32),
    }
    ref = None
    for name, arr in stores.items():
        np.save(tmp_path / f"{name}.npy", arr)
        img = NumpyPaths([str(tmp_path / f"{name}.npy")], size=16)[0]["image"]
        if ref is None:
            ref = img
        assert np.abs(img - ref).max() < 0.02, f"{name} diverges from uint8"


# -- prepare helpers (the no-network half of imagenet.py:134-242) ------------

def _tiny_jpeg(path, seed=0):
    from PIL import Image
    rng = np.random.RandomState(seed)
    Image.fromarray(rng.randint(0, 255, (20, 20, 3), np.uint8)).save(
        path, format="JPEG")


def test_prepare_imagenet_train_builds_synset_tree(tmp_path):
    import tarfile
    from dalle_tpu.data.taming_datasets import (ImageNetTrain, is_prepared,
                                                prepare_imagenet_train)

    # archive of per-synset sub-tars, like ILSVRC2012_img_train.tar
    work = tmp_path / "work"
    for si, syn in enumerate(("n01440764", "n01443537")):
        d = work / syn
        d.mkdir(parents=True)
        for i in range(2):
            _tiny_jpeg(d / f"{syn}_{i}.JPEG", seed=si * 10 + i)
    archive = tmp_path / "train.tar"
    with tarfile.open(archive, "w") as tar:
        for syn in ("n01440764", "n01443537"):
            sub = tmp_path / f"{syn}.tar"
            with tarfile.open(sub, "w") as st:
                for p in sorted((work / syn).iterdir()):
                    st.add(p, arcname=p.name)
            tar.add(sub, arcname=f"{syn}.tar")

    root = tmp_path / "prepared"
    n = prepare_imagenet_train(str(archive), str(root))
    assert n == 4 and is_prepared(root)
    files = (root / "filelist.txt").read_text().splitlines()
    assert len(files) == 4 and files == sorted(files)
    assert not list((root / "data").glob("*.tar"))   # sub-tars cleaned up
    ds = ImageNetTrain(str(root / "data"), size=16)
    assert len(ds) == 4
    item = ds[0]
    assert item["image"].shape == (16, 16, 3)
    assert item["synset"] == "n01440764" and item["class_label"] == 0
    # idempotent: second call must not re-extract
    assert prepare_imagenet_train(str(archive), str(root)) == 4


def test_prepare_imagenet_validation_reorganizes_by_synset(tmp_path):
    import tarfile
    from dalle_tpu.data.taming_datasets import (ImageNetValidation,
                                                prepare_imagenet_validation)

    flat = tmp_path / "flat"
    flat.mkdir()
    names = [f"ILSVRC2012_val_0000000{i}.JPEG" for i in range(1, 5)]
    for i, nm in enumerate(names):
        _tiny_jpeg(flat / nm, seed=i)
    archive = tmp_path / "val.tar"
    with tarfile.open(archive, "w") as tar:
        for nm in names:
            tar.add(flat / nm, arcname=nm)
    synmap = tmp_path / "validation_synset.txt"
    synmap.write_text("\n".join(
        f"{nm} {'n01440764' if i % 2 == 0 else 'n01443537'}"
        for i, nm in enumerate(names)) + "\n")

    root = tmp_path / "prepared"
    n = prepare_imagenet_validation(str(archive), str(synmap), str(root))
    assert n == 4
    ds = ImageNetValidation(str(root / "data"), size=16)
    assert len(ds) == 4
    assert {it["synset"] for it in (ds[i] for i in range(4))} == {
        "n01440764", "n01443537"}


def test_prepare_coco_layout(tmp_path):
    import json as _json
    import zipfile
    from dalle_tpu.data.taming_datasets import CocoCaptions, prepare_coco

    img_zip = tmp_path / "train2017.zip"
    with zipfile.ZipFile(img_zip, "w") as zf:
        p = tmp_path / "im.jpg"
        _tiny_jpeg(p)
        zf.write(p, "train2017/000000000001.jpg")
    ann = {"images": [{"id": 1, "file_name": "000000000001.jpg"}],
           "annotations": [{"image_id": 1, "caption": "a tiny test image"}]}
    ann_zip = tmp_path / "annotations.zip"
    with zipfile.ZipFile(ann_zip, "w") as zf:
        zf.writestr("annotations/captions_train2017.json", _json.dumps(ann))

    root = tmp_path / "coco"
    prepare_coco(str(root), images_zip=str(img_zip),
                 annotations_zip=str(ann_zip))
    ds = CocoCaptions(str(root / "train2017"),
                      str(root / "annotations/captions_train2017.json"),
                      size=16)
    assert len(ds) == 1
    assert ds[0]["caption"] == "a tiny test image"
