"""taming dataset family: item contracts over synthetic local file trees."""

import json

import numpy as np
import pytest
from PIL import Image

from dalle_tpu.data.taming_datasets import (ADE20k, CocoCaptions, CustomTest,
                                            CustomTrain, FacesHQ,
                                            ImageNetTrain, NumpyPaths)


def _png(path, size=(20, 14), color=(120, 30, 30)):
    path.parent.mkdir(parents=True, exist_ok=True)
    Image.new("RGB", size, color).save(path)


class TestCustom:
    def test_file_list(self, tmp_path):
        for i in range(3):
            _png(tmp_path / f"im{i}.png")
        lst = tmp_path / "train.txt"
        lst.write_text("\n".join(str(tmp_path / f"im{i}.png") for i in range(3)))
        ds = CustomTrain(size=8, training_images_list_file=str(lst))
        assert len(ds) == 3
        item = ds[0]
        assert item["image"].shape == (8, 8, 3)
        assert -1.0 <= item["image"].min() and item["image"].max() <= 1.0
        assert len(CustomTest(8, str(lst))) == 3


def test_numpy_paths(tmp_path):
    arr = (np.random.RandomState(0).rand(12, 12, 3) * 255).astype(np.uint8)
    np.save(tmp_path / "a.npy", arr)
    ds = NumpyPaths([str(tmp_path / "a.npy")], size=8)
    item = ds[0]
    assert item["image"].shape == (8, 8, 3)
    assert item["image"].min() >= -1.0 and item["image"].max() <= 1.0


def test_imagenet_synsets(tmp_path):
    for s, n in (("n01440764", 2), ("n01443537", 1)):
        for i in range(n):
            _png(tmp_path / s / f"{s}_{i}.JPEG".replace("JPEG", "jpeg"))
    ds = ImageNetTrain(str(tmp_path), size=8,
                       synset_to_human={"n01440764": "tench"})
    assert len(ds) == 3
    item = ds[0]
    assert item["class_label"] == 0 and item["human_label"] == "tench"
    assert item["image"].shape == (8, 8, 3)


def test_coco_captions(tmp_path):
    imgs = tmp_path / "images"
    _png(imgs / "0001.jpg")
    _png(imgs / "0002.jpg")
    ann = {"images": [{"id": 1, "file_name": "0001.jpg"},
                      {"id": 2, "file_name": "0002.jpg"}],
           "annotations": [{"image_id": 1, "caption": "a red thing"},
                           {"image_id": 1, "caption": "another view"},
                           {"image_id": 2, "caption": "a second image"}]}
    (tmp_path / "captions.json").write_text(json.dumps(ann))
    ds = CocoCaptions(str(imgs), str(tmp_path / "captions.json"), size=8)
    assert len(ds) == 2
    item = ds[0]
    assert item["caption"] in item["all_captions"]
    assert len(ds[0]["all_captions"]) == 2


def test_ade20k_segmentation(tmp_path):
    _png(tmp_path / "img" / "scene1.jpg")
    mask = Image.fromarray(np.full((10, 10), 7, np.uint8))
    (tmp_path / "ann").mkdir()
    mask.save(tmp_path / "ann" / "scene1.png")
    ds = ADE20k(str(tmp_path / "img"), str(tmp_path / "ann"), size=8)
    item = ds[0]
    assert item["segmentation"].shape == (8, 8, 151)
    assert (item["mask"] == 7).all()
    assert item["segmentation"][0, 0, 7] == 1.0


def test_faceshq_mix(tmp_path):
    for i in range(2):
        _png(tmp_path / f"celeb{i}.png")
        _png(tmp_path / f"ffhq{i}.png")
    cl = tmp_path / "celeba.txt"
    fl = tmp_path / "ffhq.txt"
    cl.write_text("\n".join(str(tmp_path / f"celeb{i}.png") for i in range(2)))
    fl.write_text("\n".join(str(tmp_path / f"ffhq{i}.png") for i in range(2)))
    ds = FacesHQ(str(cl), str(fl), size=8)
    assert len(ds) == 4
    assert ds[0]["class"] == 0 and ds[3]["class"] == 1


def test_numpy_paths_dtype_conventions(tmp_path):
    rng = np.random.RandomState(0)
    base = rng.rand(16, 16, 3)
    stores = {
        "u8": (base * 255).astype(np.uint8),
        "u16": (base * 65535).astype(np.uint16),
        "i64": (base * 255).astype(np.int64),    # numpy default int, 0-255
        "f01": base.astype(np.float32),
        "f255": (base * 255).astype(np.float32),
        "f_overshoot": np.clip(base * 1.0000001, 0, 1.0000001).astype(np.float32),
    }
    ref = None
    for name, arr in stores.items():
        np.save(tmp_path / f"{name}.npy", arr)
        img = NumpyPaths([str(tmp_path / f"{name}.npy")], size=16)[0]["image"]
        if ref is None:
            ref = img
        assert np.abs(img - ref).max() < 0.02, f"{name} diverges from uint8"
