"""VQGAN stack tests: encoder/decoder shapes, quantizers, GAN losses,
adaptive weight, two-optimizer trainer descent (taming parity surface)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_tpu.config import MeshConfig, OptimConfig, TrainConfig, VQGANConfig
from dalle_tpu.models.gan import (GANLossConfig, NLayerDiscriminator, ActNorm,
                                  adaptive_disc_weight, adopt_weight,
                                  hinge_d_loss, vanilla_d_loss)
from dalle_tpu.models.lpips import LPIPS, init_lpips
from dalle_tpu.models.vqgan import VQModel, init_vqgan
from dalle_tpu.train.trainer_vqgan import (LambdaWarmUpCosineScheduler,
                                           VQGANTrainer)

# tiny config: 32px, 2 levels (one downsample) → 16×16 latents with attention
SMALL = VQGANConfig(embed_dim=16, n_embed=64, z_channels=16, resolution=32,
                    ch=16, ch_mult=(1, 2), num_res_blocks=1,
                    attn_resolutions=(16,))


@pytest.fixture(scope="module")
def vqgan():
    return init_vqgan(SMALL, jax.random.PRNGKey(0), batch=2)


class TestVQModel:
    def test_forward_shapes(self, vqgan):
        model, params = vqgan
        img = jnp.ones((2, 32, 32, 3)) * 0.1
        recon, qloss, idx = model.apply(params, img, deterministic=True)
        assert recon.shape == (2, 32, 32, 3)
        assert qloss.shape == ()
        assert idx.shape == (2, 16, 16)

    def test_codebook_indices_and_decode_code(self, vqgan):
        model, params = vqgan
        img = jnp.linspace(-1, 1, 2 * 32 * 32 * 3).reshape(2, 32, 32, 3)
        ids = model.apply(params, img, method=VQModel.get_codebook_indices)
        assert ids.shape == (2, 256) and ids.dtype == jnp.int32
        assert (ids >= 0).all() and (ids < SMALL.n_embed).all()
        out = model.apply(params, ids, method=VQModel.decode_code)
        assert out.shape == (2, 32, 32, 3)

    def test_straight_through_gradients_reach_encoder(self, vqgan):
        model, params = vqgan
        img = jnp.ones((2, 32, 32, 3)) * 0.2

        def loss(p):
            recon, qloss, _ = model.apply(p, img, deterministic=True)
            return jnp.mean((recon - img) ** 2) + qloss

        grads = jax.grad(loss)(params)
        enc_leaves = jax.tree.leaves(grads["params"]["encoder"])
        assert any(float(jnp.abs(g).max()) > 0 for g in enc_leaves), \
            "STE must pass recon gradients through the quantizer to the encoder"

    def test_gumbel_variant(self):
        cfg = SMALL.replace(quantizer="gumbel")
        model, params = init_vqgan(cfg, jax.random.PRNGKey(1), batch=2)
        img = jnp.ones((2, 32, 32, 3)) * 0.1
        recon, qloss, idx = model.apply(
            params, img, temp=1.0, deterministic=False,
            rngs={"gumbel": jax.random.PRNGKey(2)})
        assert recon.shape == (2, 32, 32, 3) and jnp.isfinite(qloss)


class TestDiscriminator:
    def test_patchgan_output_map(self):
        disc = NLayerDiscriminator(ndf=16, n_layers=2)
        x = jnp.ones((2, 32, 32, 3))
        variables = disc.init(jax.random.PRNGKey(0), x, train=True)
        out, _ = disc.apply(variables, x, train=True, mutable=["batch_stats"])
        # 2 stride-2 convs: 32 → 8, then two stride-1 4x4 pads keep ~8
        assert out.shape[0] == 2 and out.shape[-1] == 1
        assert out.shape[1] > 1  # a patch map, not a single logit

    def test_patchgan_rejects_collapsing_resolution(self):
        """16x16 through n_layers=3 collapses to a 0x0 conv_out map whose
        mean is silently NaN (poisons the whole GAN step) — must raise."""
        disc = NLayerDiscriminator(ndf=8, n_layers=3)
        x = jnp.ones((2, 16, 16, 3))
        with pytest.raises(ValueError, match="reduce disc_num_layers"):
            disc.init(jax.random.PRNGKey(0), x, train=True)

    def test_actnorm_data_dependent_init(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (8, 4, 4, 3)) * 5 + 2
        an = ActNorm()
        params = an.init(jax.random.PRNGKey(1), x)
        y = an.apply(params, x)
        # after data-dependent init the first batch is ~zero-mean unit-var
        assert abs(float(y.mean())) < 1e-3
        assert abs(float(y.std()) - 1.0) < 1e-2

    def test_actnorm_discriminator_has_no_batch_stats(self):
        disc = NLayerDiscriminator(ndf=16, n_layers=2, use_actnorm=True)
        x = jnp.ones((2, 32, 32, 3))
        variables = disc.init(jax.random.PRNGKey(0), x, train=True)
        assert "batch_stats" not in variables


class TestGANLosses:
    def test_hinge_and_vanilla_zero_crossing(self):
        real = jnp.ones((4, 4, 4, 1)) * 10.0   # confident real
        fake = -jnp.ones((4, 4, 4, 1)) * 10.0  # confident fake
        assert float(hinge_d_loss(real, fake)) == pytest.approx(0.0)
        assert float(vanilla_d_loss(real, fake)) == pytest.approx(0.0, abs=1e-3)
        # wrong-way logits are penalized
        assert float(hinge_d_loss(fake, real)) > 1.0

    def test_adopt_weight_gates_on_step(self):
        assert float(adopt_weight(1.0, jnp.int32(5), threshold=10)) == 0.0
        assert float(adopt_weight(1.0, jnp.int32(15), threshold=10)) == 1.0

    @pytest.mark.slow  # ~14s (VGG compile); LPIPS parity keeps its stronger
    # fast-tier check against the torch oracle in test_golden_import
    def test_lpips_zero_for_identical_inputs(self):
        model, params = init_lpips(jax.random.PRNGKey(0), 32)
        x = jax.random.uniform(jax.random.PRNGKey(1), (2, 32, 32, 3)) * 2 - 1
        d = model.apply(params, x, x)
        assert d.shape == (2,)
        assert float(jnp.abs(d).max()) == pytest.approx(0.0, abs=1e-6)
        y = jnp.clip(x + 0.5, -1, 1)
        assert float(model.apply(params, x, y).mean()) > 0

    def test_adaptive_weight_finite_positive(self, vqgan):
        model, params = vqgan
        img = jax.random.uniform(jax.random.PRNGKey(3), (2, 32, 32, 3)) * 2 - 1
        q = model.apply(params, img, deterministic=True, method=VQModel.encode)
        recon, h_last = model.apply(params, q.quantized, True, True,
                                    method=VQModel.decode)
        disc = NLayerDiscriminator(ndf=16, n_layers=2)
        dvars = disc.init(jax.random.PRNGKey(4), img, train=True)

        def nll_of(r):
            return jnp.mean(jnp.abs(img - r))

        def g_of(r):
            out, _ = disc.apply(dvars, r, train=True, mutable=["batch_stats"])
            return -jnp.mean(out)

        w = adaptive_disc_weight(nll_of, g_of, h_last,
                                 params["params"]["decoder"]["conv_out"], 0.8)
        assert jnp.isfinite(w) and float(w) >= 0


class TestScheduler:
    def test_warmup_then_cosine(self):
        s = LambdaWarmUpCosineScheduler(10, 0.0, 1.0, 0.1, 110)
        assert s(0) == pytest.approx(0.1)
        assert s(10) == pytest.approx(1.0)
        assert s(110) == pytest.approx(0.0, abs=1e-9)
        assert s(1000) == pytest.approx(0.0, abs=1e-9)  # clamped past the end


class TestTrainer:
    @pytest.mark.slow
    @pytest.mark.parametrize("quantizer", ["vq", "gumbel"])
    def test_loss_decreases(self, tmp_path, quantizer):
        cfg = SMALL.replace(quantizer=quantizer)
        tc = TrainConfig(batch_size=8, log_every=1000, save_every_steps=10_000,
                         checkpoint_dir=str(tmp_path / "ckpt"),
                         preflight_checkpoint=False,
                         mesh=MeshConfig(dp=2),
                         optim=OptimConfig(learning_rate=2e-3, beta1=0.5,
                                           beta2=0.9, grad_clip_norm=0.0))
        # disc off (disc_start far away) so the descent signal is pure recon
        lc = GANLossConfig(disc_start=10_000, perceptual_weight=0.0)
        tr = VQGANTrainer(cfg, tc, loss_cfg=lc)
        rng = np.random.RandomState(0)
        imgs = rng.rand(8, 32, 32, 3).astype(np.float32) * 2 - 1
        first = tr.train_step(imgs)["nll_loss"]
        for _ in range(15):
            m = tr.train_step(imgs)
        assert m["nll_loss"] < first

    @pytest.mark.slow  # ~37s (two-optimizer GAN step compile); the gate
    # math keeps fast-tier units (adopt_weight, disc forward/actnorm) and
    # test_perceptual still compiles+steps a VQGANTrainer fast-tier — the
    # disc-updates integration rides the slow tier with loss_decreases
    def test_disc_trains_after_start(self, tmp_path):
        tc = TrainConfig(batch_size=8, log_every=1000, save_every_steps=10_000,
                         checkpoint_dir=str(tmp_path / "ckpt"),
                         preflight_checkpoint=False, mesh=MeshConfig(dp=2),
                         optim=OptimConfig(learning_rate=1e-3, beta1=0.5,
                                           beta2=0.9, grad_clip_norm=0.0))
        lc = GANLossConfig(disc_start=0, perceptual_weight=0.0)
        tr = VQGANTrainer(SMALL, tc, loss_cfg=lc)
        rng = np.random.RandomState(1)
        imgs = rng.rand(8, 32, 32, 3).astype(np.float32) * 2 - 1
        before = jax.device_get(tr.state.params["disc"])
        m = tr.train_step(imgs)
        after = jax.device_get(tr.state.params["disc"])
        changed = jax.tree.map(lambda a, b: bool(np.any(a != b)), before, after)
        assert any(jax.tree.leaves(changed)), "disc params must update"
        assert np.isfinite(m["disc_loss"]) and np.isfinite(m["d_weight"])


class TestVariantModes:
    @pytest.mark.slow
    def test_nodisc_mode_trains(self, tmp_path):
        tc = TrainConfig(batch_size=8, log_every=1000, save_every_steps=10_000,
                         checkpoint_dir=str(tmp_path / "ck"),
                         preflight_checkpoint=False, mesh=MeshConfig(dp=8),
                         optim=OptimConfig(learning_rate=2e-3, grad_clip_norm=0.0))
        tr = VQGANTrainer(SMALL, tc, loss_mode="nodisc")
        imgs = np.random.RandomState(0).rand(8, 32, 32, 3).astype("float32") * 2 - 1
        first = tr.train_step(imgs)["nll_loss"]
        for _ in range(10):
            m = tr.train_step(imgs)
        assert m["nll_loss"] < first
        ids = tr.get_codebook_indices(imgs[:2])
        assert ids.shape == (2, 256)

    @pytest.mark.slow
    def test_segmentation_mode(self, tmp_path):
        # VQSegmentationModel: out_ch = n_labels, BCE-with-quant loss
        cfg = SMALL.replace(out_ch=8)
        tc = TrainConfig(batch_size=8, log_every=1000, save_every_steps=10_000,
                         checkpoint_dir=str(tmp_path / "ck"),
                         preflight_checkpoint=False, mesh=MeshConfig(dp=8),
                         optim=OptimConfig(learning_rate=2e-3, grad_clip_norm=0.0))
        tr = VQGANTrainer(cfg, tc, loss_mode="segmentation")
        rng = np.random.RandomState(0)
        imgs = rng.rand(8, 32, 32, 3).astype("float32") * 2 - 1
        seg = np.eye(8, dtype="float32")[rng.randint(0, 8, (8, 32, 32))]
        first = tr.train_step(imgs, seg)["nll_loss"]
        for _ in range(10):
            m = tr.train_step(imgs, seg)
        assert m["nll_loss"] < first


class TestRemap:
    """Index remapping onto a used-codes subset (taming quantize.py:238-256,
    303-310: remap/unknown_index/sane_index_shape)."""

    def test_remap_unmap_round_trip(self):
        from dalle_tpu.ops.quantize import remap_indices, unmap_indices
        used = (3, 7, 11, 42)
        idx = jnp.asarray([[3, 42, 7], [11, 3, 11]])
        re = remap_indices(idx, used)
        assert re.tolist() == [[0, 3, 1], [2, 0, 2]]
        back = unmap_indices(re, used)
        assert back.tolist() == idx.tolist()

    def test_unknown_modes(self):
        from dalle_tpu.ops.quantize import remap_indices, unmap_indices
        used = (3, 7)
        idx = jnp.asarray([5, 3])          # 5 is not a used code
        extra = remap_indices(idx, used, unknown="extra")
        assert extra.tolist() == [2, 0]
        # 'extra' collapses to used[0] on the way back
        assert unmap_indices(extra, used).tolist() == [3, 3]
        fixed = remap_indices(idx, used, unknown=1)
        assert fixed.tolist() == [1, 0]
        rand = remap_indices(idx, used, unknown="random",
                             key=jax.random.PRNGKey(0))
        assert 0 <= int(rand[0]) < len(used) and int(rand[1]) == 0

    def test_vqmodel_remap_interface(self, rng):
        cfg = VQGANConfig(resolution=16, ch=8, ch_mult=(1, 2),
                          num_res_blocks=1, attn_resolutions=(8,),
                          z_channels=4, embed_dim=4, n_embed=16,
                          remap_used=(0, 2, 5, 9, 13), remap_unknown="extra")
        model, params = init_vqgan(cfg, jax.random.PRNGKey(0))
        img = jnp.asarray(rng.rand(2, 16, 16, 3).astype(np.float32) * 2 - 1)
        ids = model.apply(params, img, method=VQModel.get_codebook_indices)
        assert int(jnp.max(ids)) <= len(cfg.remap_used)  # used ids + extra
        rec = model.apply(params, ids, method=VQModel.decode_code)
        assert rec.shape == (2, 16, 16, 3)
        assert bool(jnp.all(jnp.isfinite(rec)))
