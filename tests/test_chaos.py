"""graftmend chaos-lite tier-1 tests (docs/RESILIENCE.md): the fault classes
that don't need subprocesses — retry-decorator semantics incl. budget
exhaustion and obs counters, FaultPlan scripting/scoping/injection,
checkpoint stale-tmp GC + corruption fallback, breach→action
edge-triggering for all three policy actions, SIGTERM graceful preemption
at the fit level, and the elastic membership/heartbeat/agent machinery
(agent tests drive real — but jax-free — python children). The real
2-process gloo/DCN recovery scenarios live in scripts/chaos_smoke.py (CI
stage) and the slow tier below it."""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from dalle_tpu import chaos, obs
from dalle_tpu.chaos import Fault, FaultPlan, InjectedFault
from dalle_tpu.chaos.faults import corrupt_checkpoint
from dalle_tpu.config import DVAEConfig, TrainConfig
from dalle_tpu.obs.anomaly import Breach, HealthSentry, NaNPrecursorDetector
from dalle_tpu.parallel import elastic
from dalle_tpu.train.actions import BreachActions
from dalle_tpu.train.base_trainer import BaseTrainer
from dalle_tpu.train.checkpoints import CheckpointManager
from dalle_tpu.train.metrics import ThroughputMeter
from dalle_tpu.train.train_state import TrainState
from dalle_tpu.utils.retry import (RetryBudgetExceeded, backoff_delays,
                                   retry, with_retry)

pytestmark = pytest.mark.recompile_budget(120)

NO_SLEEP = {"sleep": lambda s: None}


@pytest.fixture(autouse=True)
def _clean_obs_and_chaos():
    """Fresh tracer (counters are global) and no leaked FaultPlan/recorder
    between tests."""
    obs.disable()
    obs.configure()
    yield
    chaos.uninstall()
    obs.disable_recorder()
    obs.disable()


def counters():
    return obs.metrics_snapshot()


# ---------------------------------------------------------------------------
# retry layer
# ---------------------------------------------------------------------------

def test_retry_absorbs_transient_failures_with_counters():
    calls = []

    @retry("op_a", attempts=4, sleep=lambda s: calls.append(("sleep", s)),
           seed=7)
    def flaky():
        calls.append(("try",))
        if sum(1 for c in calls if c[0] == "try") < 3:
            raise OSError("blip")
        return "done"

    assert flaky() == "done"
    assert sum(1 for c in calls if c[0] == "try") == 3
    # the two backoff sleeps follow the seeded schedule exactly
    slept = [s for kind, *rest in calls if kind == "sleep" for s in rest]
    assert slept == backoff_delays(4, seed=7)[:2]
    snap = counters()
    assert snap['retry.attempts_total{op="op_a"}'] == 2
    assert snap['retry.recovered_total{op="op_a"}'] == 1
    assert 'retry.exhausted_total{op="op_a"}' not in snap


def test_retry_budget_exhaustion_chains_the_root_cause():
    @retry("op_b", attempts=3, **NO_SLEEP)
    def always():
        raise ConnectionError("down")

    with pytest.raises(RetryBudgetExceeded) as ei:
        always()
    assert isinstance(ei.value.__cause__, ConnectionError)
    assert ei.value.attempts == 3
    snap = counters()
    assert snap['retry.attempts_total{op="op_b"}'] == 3
    assert snap['retry.exhausted_total{op="op_b"}'] == 1


def test_retry_non_transient_propagates_immediately():
    calls = []

    @retry("op_c", attempts=5, **NO_SLEEP)
    def broken():
        calls.append(1)
        raise ValueError("deterministic bug")

    with pytest.raises(ValueError):
        broken()
    assert calls == [1]          # no retry burned hiding a real bug
    assert 'retry.attempts_total{op="op_c"}' not in counters()


def test_backoff_schedule_deterministic_and_bounded():
    a = backoff_delays(6, base_delay_s=0.05, max_delay_s=0.4, jitter=0.5,
                       seed=3)
    assert a == backoff_delays(6, base_delay_s=0.05, max_delay_s=0.4,
                               jitter=0.5, seed=3)
    assert len(a) == 5
    for i, d in enumerate(a):
        nominal = min(0.05 * 2 ** i, 0.4)
        assert 0.5 * nominal <= d <= 1.5 * nominal


def test_with_retry_call_form():
    calls = []

    def op(x):
        calls.append(x)
        if len(calls) < 2:
            raise TimeoutError
        return x * 2

    assert with_retry("op_d", op, 21, retry_kw=dict(NO_SLEEP)) == 42
    assert calls == [21, 21]


# ---------------------------------------------------------------------------
# fault plan
# ---------------------------------------------------------------------------

def test_fault_plan_env_roundtrip_with_rank_and_epoch():
    plan = FaultPlan([Fault(kind="kill", step=3, rank=1, signal="SIGTERM"),
                      Fault(kind="fail_io", site="ckpt_save", times=2)],
                     seed=9)
    env = dict(plan.env())
    env[chaos.RANK_ENV] = "1"
    env[chaos.EPOCH_ENV] = "2"
    installed = chaos.install_from_env(env)
    assert installed is chaos.active_plan()
    assert installed.rank == 1 and installed.epoch == 2
    assert installed.seed == 9
    assert [f.kind for f in installed.faults] == ["kill", "fail_io"]


def test_fail_io_fires_times_then_heals():
    chaos.install(FaultPlan([Fault(kind="fail_io", site="ckpt_save",
                                   times=2)]))
    for _ in range(2):
        with pytest.raises(InjectedFault):
            chaos.io_hook("ckpt_save")
    chaos.io_hook("ckpt_save")           # healed
    chaos.io_hook("ckpt_restore")        # other sites never affected
    assert counters()['chaos.faults_injected_total{kind="fail_io"}'] == 2


def test_fault_scoping_by_rank_and_epoch():
    faults = [Fault(kind="fail_io", site="heartbeat", rank=1, times=5),
              Fault(kind="fail_io", site="ckpt_save", epoch=0, times=5)]
    # wrong rank: rank-1 fault silent on rank 0
    chaos.install(FaultPlan(faults, rank=0))
    chaos.io_hook("heartbeat")
    # right rank fires
    chaos.install(FaultPlan(faults, rank=1))
    with pytest.raises(InjectedFault):
        chaos.io_hook("heartbeat")
    # a respawned worker in epoch 1 must NOT re-fire epoch-0 faults
    chaos.install(FaultPlan(faults, rank=0, epoch=1))
    chaos.io_hook("ckpt_save")


def test_step_faults_slow_and_kill(monkeypatch):
    sleeps, kills = [], []
    monkeypatch.setattr(chaos.faults.time, "sleep",
                        lambda s: sleeps.append(s))
    monkeypatch.setattr(chaos.faults.os, "kill",
                        lambda pid, sig: kills.append((pid, sig)))
    chaos.install(FaultPlan([
        Fault(kind="slow", step=2, span_steps=2, duration_s=0.5),
        Fault(kind="kill", step=4, signal="SIGTERM")]))
    for s in range(6):
        chaos.step_hook(s)
    assert sleeps == [0.5, 0.5]          # slowed exactly steps 2 and 3
    assert kills == [(os.getpid(), signal.SIGTERM)]   # fired once, at 4
    assert counters()['chaos.faults_injected_total{kind="kill"}'] == 1


def test_wedge_fault_blocks_engine_loop_once(monkeypatch):
    """`wedge` = `hang` named for the serving plane (graftward): blocks
    inside the engine's step hook for duration_s, fires once, and
    roundtrips the env handoff like every other kind."""
    sleeps = []
    monkeypatch.setattr(chaos.faults.time, "sleep",
                        lambda s: sleeps.append(s))
    plan = FaultPlan([Fault(kind="wedge", step=9, duration_s=600.0)])
    plan2 = FaultPlan.from_json(plan.env()[chaos.PLAN_ENV])
    chaos.install(plan2)
    for s in range(12):
        chaos.step_hook(s)
    assert sleeps == [600.0]             # one wedge, at step 9, latched
    assert counters()['chaos.faults_injected_total{kind="wedge"}'] == 1


def test_plan_sample_is_seed_deterministic():
    a = FaultPlan.sample(5, nproc=3, max_step=10, kinds=("kill", "fail_io"))
    b = FaultPlan.sample(5, nproc=3, max_step=10, kinds=("kill", "fail_io"))
    assert a.to_json() == b.to_json()
    c = FaultPlan.sample(6, nproc=3, max_step=10, kinds=("kill", "fail_io"))
    assert c.to_json() != a.to_json()


def test_corrupt_checkpoint_tmp_litter_and_truncate(tmp_path):
    d = str(tmp_path)
    os.makedirs(os.path.join(d, "4"))
    with open(os.path.join(d, "4", "data.bin"), "wb") as fh:
        fh.write(b"x" * 64)
    planted = corrupt_checkpoint(d, mode="tmp_litter", age_s=5000)[0]
    assert ".orbax-checkpoint-tmp" in planted
    assert time.time() - os.path.getmtime(planted) > 4000
    touched = corrupt_checkpoint(d, mode="truncate")
    assert touched and os.path.getsize(touched[0]) == 0


# ---------------------------------------------------------------------------
# checkpoint hardening (real orbax over tiny trees)
# ---------------------------------------------------------------------------

def _mgr(tmp_path, **kw):
    m = CheckpointManager(str(tmp_path), async_save=False, **kw)
    m.retry_kw = dict(m.retry_kw, sleep=lambda s: None)
    return m


STATE = {"w": jnp.arange(4.0), "b": jnp.zeros(2)}


def test_gc_stale_tmp_reclaims_old_keeps_fresh(tmp_path):
    m = _mgr(tmp_path)
    stale = corrupt_checkpoint(str(tmp_path), mode="tmp_litter",
                               age_s=10_000)[0]
    fresh = os.path.join(str(tmp_path), "8888.orbax-checkpoint-tmp-1")
    os.makedirs(fresh)
    reclaimed = m.gc_stale_tmp(log=lambda *a: None)
    assert reclaimed == [stale]
    assert not os.path.exists(stale) and os.path.exists(fresh)
    assert counters()["ckpt.tmp_reclaimed_total"] == 1
    m.close()


def test_gc_runs_on_restore_and_preflight(tmp_path):
    m = _mgr(tmp_path)
    m.save(1, STATE)
    stale = corrupt_checkpoint(str(tmp_path), mode="tmp_litter",
                               age_s=10_000)[0]
    m.restore(STATE, log=lambda *a: None)
    assert not os.path.exists(stale)
    stale2 = corrupt_checkpoint(str(tmp_path), mode="tmp_litter",
                                age_s=10_000)[0]
    m.preflight(STATE)
    assert not os.path.exists(stale2)
    m.close()


def test_restore_falls_back_past_corrupt_step_and_quarantines(tmp_path):
    m = _mgr(tmp_path)
    m.save(1, {"w": jnp.arange(4.0) * 1, "b": jnp.zeros(2)})
    m.save(2, {"w": jnp.arange(4.0) * 2, "b": jnp.zeros(2)})
    corrupt_checkpoint(str(tmp_path), mode="truncate")      # newest = 2
    restored, _meta = m.restore(STATE, log=lambda *a: None)
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.arange(4.0))           # step 1 state
    assert counters()["ckpt.restore_fallback_total"] >= 1
    assert os.path.isdir(os.path.join(str(tmp_path), "2.corrupt"))
    # the quarantined step number is reusable: resumed training re-saves 2
    m.save(2, STATE)
    m.close()


def test_restore_every_step_failing_raises_and_quarantines_nothing(tmp_path):
    """Quarantine is deferred until SOME step restores: when every step
    fails (all-corrupt here, but equally a template↔checkpoint tree
    mismatch or a broken reader), the error propagates with the on-disk
    history untouched — a systemic failure must not rename it away."""
    m = _mgr(tmp_path)
    m.save(1, STATE)
    m.save(2, STATE)
    corrupt_checkpoint(str(tmp_path), mode="truncate")
    shutil_target = os.path.join(str(tmp_path), "1")
    corrupt_checkpoint(str(tmp_path), mode="truncate")  # hits newest again
    for dirpath, _d, files in os.walk(shutil_target):
        for fn in files:
            open(os.path.join(dirpath, fn), "wb").close()
    with pytest.raises(RuntimeError, match="failed to restore"):
        m.restore(STATE, log=lambda *a: None)
    assert os.path.isdir(os.path.join(str(tmp_path), "1"))
    assert os.path.isdir(os.path.join(str(tmp_path), "2"))
    assert not any(n.endswith(".corrupt") for n in os.listdir(str(tmp_path)))
    m.close()


def test_pinned_restore_does_not_fall_back(tmp_path):
    m = _mgr(tmp_path)
    m.save(1, STATE)
    m.save(2, STATE)
    corrupt_checkpoint(str(tmp_path), mode="truncate")
    with pytest.raises(Exception):
        m.restore(STATE, step=2, log=lambda *a: None)
    assert os.path.isdir(os.path.join(str(tmp_path), "2"))  # not quarantined
    m.close()


def test_transient_restore_exhaustion_does_not_quarantine(tmp_path):
    """RetryBudgetExceeded is an INFRASTRUCTURE failure, not corruption:
    the fallback must re-raise instead of renaming a healthy newest step
    to .corrupt and silently resuming from older progress."""
    from dalle_tpu.utils.retry import RetryBudgetExceeded
    m = _mgr(tmp_path)
    m.save(1, STATE)
    m.save(2, STATE)
    chaos.install(FaultPlan([Fault(kind="fail_io", site="ckpt_restore",
                                   times=99)]))
    with pytest.raises(RetryBudgetExceeded):
        m.restore(STATE, log=lambda *a: None)
    chaos.uninstall()
    assert os.path.isdir(os.path.join(str(tmp_path), "2"))
    assert not os.path.isdir(os.path.join(str(tmp_path), "2.corrupt"))
    # healed I/O: the same newest step restores fine afterwards
    restored, _ = m.restore(STATE, log=lambda *a: None)
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.asarray(STATE["w"]))
    m.close()


def test_vanished_step_skipped_without_quarantine(tmp_path):
    """In a pod every member races the same fallback: a step a PEER
    already quarantined reads as FileNotFoundError here — skip it (there
    is nothing to quarantine) and keep falling back, never crash."""
    from dalle_tpu.utils.retry import RetryBudgetExceeded
    m = _mgr(tmp_path)
    m.save(1, STATE)
    m.save(2, STATE)
    real = m._restore_step

    def racing(template, step):
        if step == 2:
            raise RetryBudgetExceeded(
                "ckpt_restore", 4, FileNotFoundError("peer renamed it"))
        return real(template, step)

    m._restore_step = racing
    restored, _ = m.restore(STATE, log=lambda *a: None)
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.asarray(STATE["w"]))
    assert not os.path.isdir(os.path.join(str(tmp_path), "2.corrupt"))
    m.close()


def test_injected_ckpt_io_faults_absorbed_by_retry(tmp_path):
    m = _mgr(tmp_path)
    chaos.install(FaultPlan([
        Fault(kind="fail_io", site="ckpt_save", times=2),
        Fault(kind="fail_io", site="ckpt_restore", times=1)]))
    m.save(3, STATE)                      # absorbed, not a crash
    restored, _ = m.restore(STATE, log=lambda *a: None)
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.asarray(STATE["w"]))
    snap = counters()
    assert snap['retry.attempts_total{op="ckpt_save"}'] == 2
    assert snap['retry.recovered_total{op="ckpt_save"}'] == 1
    assert snap['retry.attempts_total{op="ckpt_restore"}'] == 1
    m.close()


# ---------------------------------------------------------------------------
# breach→action automation
# ---------------------------------------------------------------------------

class TinyTrainer(BaseTrainer):
    """Real TrainState + rollback machinery over a 2-element param tree —
    no model, no mesh, no compiled step; the action layer under test is
    pure host code over real jax arrays."""

    model_class = "Tiny"

    def __init__(self, tmp_path):
        self.train_cfg = TrainConfig(
            checkpoint_dir=str(tmp_path), preflight_checkpoint=False,
            rollback_snapshot="host")
        self.model_cfg = DVAEConfig()
        self.ckpt = None
        self.meter = ThroughputMeter(4, 1)
        self.extra_meta = {}
        self._host_step = 0
        self.state = TrainState.create(
            apply_fn=lambda p, x: x, params={"w": jnp.ones(3)},
            tx=optax.sgd(0.1), lr_scale=1.0)
        self.reanneals = []

    def reanneal_gumbel(self, step):
        self.reanneals.append(step)
        return 1.0


def test_each_policy_action_fires_and_is_recorded(tmp_path):
    obs.configure_recorder(str(tmp_path / "flight"), min_dump_interval_s=0.0)
    tr = TinyTrainer(tmp_path)
    tr._snapshot_good()
    acts = BreachActions(tr, log=lambda *a: None).attach()
    assert tr.health_sentry is not None and tr.health_sentry.on_breach is acts

    acts(Breach("nan-precursor", "enc", 1, 0.01, 0.0, "inj"))
    assert tr._preemptive_good is not None

    tr.state = tr.state.replace(params={"w": jnp.zeros(3)})
    acts(Breach("grad-explosion", "dec", 2, 99.0, 5.0, "inj"))
    assert float(jnp.asarray(tr.state.lr_scale)) == pytest.approx(0.5)

    acts(Breach("codebook-collapse", "codebook", 3, 1.0, 4.0, "inj"))
    assert float(jnp.asarray(tr.state.lr_scale)) == pytest.approx(0.25)
    assert tr.reanneals == [3]

    assert [a[1] for a in acts.fired] == [
        "preemptive_snapshot", "rollback_lr_cut", "lr_cut_reanneal"]
    events = [e for e in obs.get_recorder().events
              if e.get("kind") == "breach_action"]
    assert [e["action"] for e in events] == [a[1] for a in acts.fired]
    snap = counters()
    for action in ("preemptive_snapshot", "rollback_lr_cut",
                   "lr_cut_reanneal"):
        assert snap[f'actions.fired_total{{action="{action}"}}'] == 1


def test_exactly_one_action_per_breach_edge(tmp_path):
    """The sentry is edge-triggered and the action layer coalesces: a
    sustained nan-precursor breach fires ONE preemptive snapshot, re-armed
    only after recovery."""
    tr = TinyTrainer(tmp_path)
    tr._snapshot_good()
    tr.health_sentry = HealthSentry([NaNPrecursorDetector()],
                                    dump_bundles=False)
    acts = BreachActions(tr, log=lambda *a: None).attach()
    bad = {"health/nonfinite_frac/enc": 0.3}
    good = {"health/nonfinite_frac/enc": 0.0}
    tr.health_sentry.observe(1, dict(bad))
    tr.health_sentry.observe(2, dict(bad))     # still in breach: no re-fire
    tr.health_sentry.observe(3, dict(bad))
    assert len(acts.fired) == 1
    tr.health_sentry.observe(4, dict(good))    # recovery re-arms
    tr.health_sentry.observe(5, dict(bad))
    assert len(acts.fired) == 2


def test_same_step_multi_group_breaches_coalesce(tmp_path):
    tr = TinyTrainer(tmp_path)
    tr._snapshot_good()
    acts = BreachActions(tr, log=lambda *a: None)
    acts(Breach("grad-explosion", "enc", 7, 9.0, 1.0, "inj"))
    acts(Breach("grad-explosion", "dec", 7, 8.0, 1.0, "inj"))
    assert len(acts.fired) == 1      # five subtrees exploding ≠ 5 rollbacks


def test_lr_cut_clamps_at_min_scale(tmp_path):
    tr = TinyTrainer(tmp_path)
    tr._snapshot_good()
    acts = BreachActions(tr, lr_cut_factor=0.1, min_lr_scale=0.05,
                         log=lambda *a: None)
    for step in range(1, 4):
        acts(Breach("grad-explosion", "enc", step * 2, 9.0, 1.0, "inj"))
    assert float(jnp.asarray(tr.state.lr_scale)) == pytest.approx(0.05)


def test_lr_scale_actually_scales_the_applied_update():
    st = TrainState.create(apply_fn=None, params={"w": jnp.ones(3)},
                           tx=optax.sgd(0.1), lr_scale=1.0)
    grads = {"w": jnp.ones(3)}
    full = st.apply_gradients(grads)
    halved = st.replace(lr_scale=jnp.float32(0.5)).apply_gradients(grads)
    np.testing.assert_allclose(
        np.asarray(halved.params["w"]) - np.asarray(st.params["w"]),
        0.5 * (np.asarray(full.params["w"]) - np.asarray(st.params["w"])),
        rtol=1e-6)


def test_lr_scale_is_opt_in_and_absent_by_default():
    """Default states carry NO lr_scale leaf: the compiled step must stay
    byte-identical to the scale-less program (the leaf's per-param multiply
    taxes compile time across every trainer program — measured ~11% on the
    dalle trainer module), and the graftir goldens pin that. Armed states
    get the leaf at CREATE time only."""
    base = TrainState.create(apply_fn=None, params={"w": jnp.ones(3)},
                             tx=optax.sgd(0.1))
    assert base.lr_scale is None
    assert len(jax.tree_util.tree_leaves((base.lr_scale,))) == 0
    # un-armed apply_gradients is the plain update (no scale multiply)
    stepped = base.apply_gradients({"w": jnp.ones(3)})
    np.testing.assert_allclose(np.asarray(stepped.params["w"]),
                               np.ones(3) - 0.1, rtol=1e-6)


def test_preemptive_snapshot_rollback_ladder(tmp_path):
    """First rollback consumes the precursor rung; a repeat NaN falls
    through to the durable boundary snapshot — the ladder never loops on a
    poisoned rung."""
    tr = TinyTrainer(tmp_path)
    tr.state = tr.state.replace(params={"w": jnp.ones(3) * 10})
    tr._snapshot_good()                                   # boundary: 10s
    tr.state = tr.state.replace(params={"w": jnp.ones(3) * 20})
    tr.take_preemptive_snapshot()                         # rung: 20s
    tr.state = tr.state.replace(params={"w": jnp.ones(3) * 30})
    tr._rollback()
    np.testing.assert_array_equal(np.asarray(tr.state.params["w"]),
                                  np.ones(3) * 20)        # rung consumed
    tr._rollback()
    np.testing.assert_array_equal(np.asarray(tr.state.params["w"]),
                                  np.ones(3) * 10)        # boundary snapshot


def test_boundary_snapshot_supersedes_preemptive_rung(tmp_path):
    tr = TinyTrainer(tmp_path)
    tr.state = tr.state.replace(params={"w": jnp.ones(3) * 5})
    tr.take_preemptive_snapshot()
    tr.state = tr.state.replace(params={"w": jnp.ones(3) * 6})
    tr._snapshot_good()     # newer durable point: the stale rung must die
    tr._rollback()
    np.testing.assert_array_equal(np.asarray(tr.state.params["w"]),
                                  np.ones(3) * 6)


def test_lr_cut_skips_states_without_the_field(tmp_path):
    """GANTrainState (full-GAN VQGAN) has no lr_scale FIELD at all — the
    cut must degrade to a logged skip, not an AttributeError that eats
    the action after the rollback already ran."""
    logs = []
    tr = TinyTrainer(tmp_path)

    class FieldlessState:
        params = {"w": jnp.ones(1)}
        opt_state = {}

    tr.state = FieldlessState()
    acts = BreachActions(tr, log=logs.append)
    assert acts._cut_lr() == 1.0
    assert any("skipped" in l for l in logs)


def test_action_failure_degrades_to_log_not_crash(tmp_path):
    logs = []
    tr = TinyTrainer(tmp_path)
    acts = BreachActions(tr, log=logs.append)
    acts._handlers["rollback_lr_cut"] = lambda b: 1 / 0
    acts(Breach("grad-explosion", "enc", 1, 9.0, 1.0, "inj"))
    assert acts.fired == []
    assert any("failed" in l for l in logs)


def test_reanneal_rebase_survives_checkpoint_restore(tmp_path):
    """The codebook-collapse remediation must survive the preemption/
    respawn this same PR makes routine: the re-anneal rebase rides
    checkpoint metadata, so a respawned trainer resumes the re-warmed
    schedule instead of snapping back to the cold temperature."""
    from dalle_tpu.config import AnnealConfig, DVAEConfig
    from dalle_tpu.train.trainer_vae import VAETrainer
    cfg = DVAEConfig(image_size=16, num_tokens=16, codebook_dim=8,
                     num_layers=1, num_resnet_blocks=0, hidden_dim=8)
    tc = TrainConfig(batch_size=2, checkpoint_dir=str(tmp_path),
                     preflight_checkpoint=False, async_checkpointing=False,
                     save_every_steps=100)
    anneal = AnnealConfig(starting_temp=1.0, anneal_rate=0.1, temp_min=0.1)
    tr = VAETrainer(cfg, tc, anneal_cfg=anneal)
    tr._host_step = 40
    warmed = tr.reanneal_gumbel(40)
    assert warmed == pytest.approx(1.0)          # schedule restarted
    tr.state = tr.state.replace(step=jnp.asarray(40))
    tr.ckpt.save(40, tr.state, tr._meta())

    fresh = VAETrainer(cfg, tc, anneal_cfg=anneal)
    fresh.restore()
    assert fresh._anneal_step0 == 40
    assert fresh._temp_at(41) == pytest.approx(tr._temp_at(41))


# ---------------------------------------------------------------------------
# SIGTERM graceful preemption (fit level)
# ---------------------------------------------------------------------------

class RecordingCkpt:
    def __init__(self):
        self.saves = []
        self.metas = []
        self.drains = 0

    def preflight(self, state, meta=None):
        pass

    def save(self, step, state, meta=None):
        self.saves.append(step)
        self.metas.append(dict(meta or {}))

    def wait_until_finished(self):
        self.drains += 1


class FakeTrainer(BaseTrainer):
    model_class = "Fake"

    def __init__(self, tc):
        self.train_cfg = tc
        self.model_cfg = DVAEConfig()
        self.ckpt = RecordingCkpt()
        self.meter = ThroughputMeter(tc.batch_size, tc.log_every)
        self.extra_meta = {}
        self.state = None
        self._host_step = 0
        self._obs_dispatch_t0 = None
        self._obs_last_wait = 0.0
        self._obs_wait_accum = 0.0
        self._obs_window_t0 = None

    def train_step(self, x):
        return self._finish_step({"loss": np.float32(0.5)})

    def _snapshot_good(self):
        pass


def test_sigterm_finishes_step_saves_drains_and_exits_fit(tmp_path):
    """The k8s/TPU-preemption contract: a real SIGTERM mid-run finishes the
    in-flight step, forces a synchronous drained save through the
    signal-latch path, and fit returns early with ``preempted`` set (the
    CLI then exits 0)."""
    tc = TrainConfig(checkpoint_dir=str(tmp_path), batch_size=4,
                     log_every=100, save_every_steps=100,
                     preflight_checkpoint=False, device_prefetch=0)
    tr = FakeTrainer(tc)
    tr.install_preemption_handler(log=lambda *a: None)
    consumed = []

    def batches():
        for i in range(10):
            if i == 3:
                os.kill(os.getpid(), signal.SIGTERM)   # the real signal
            consumed.append(i)
            yield (np.zeros((4, 8), np.float32),)

    tr.fit(batches(), steps=10, log=lambda *a: None)
    assert tr.preempted
    # the in-flight step (the one the signal landed in) completed and was
    # saved synchronously + drained; nothing after it ran
    assert tr._host_step == 4
    assert tr.ckpt.saves == [4]
    assert tr.ckpt.drains >= 1
    assert consumed == [0, 1, 2, 3]


def test_fit_saves_carry_current_extra_meta(tmp_path):
    """fit must re-evaluate _meta() at each save: extra_meta changes
    mid-run (the gumbel re-anneal action records its rebase there) and a
    stale snapshot taken before the loop would strand every later
    checkpoint's sidecar on the pre-breach values."""
    tc = TrainConfig(checkpoint_dir=str(tmp_path), batch_size=4,
                     log_every=100, save_every_steps=2,
                     preflight_checkpoint=False, device_prefetch=0)
    tr = FakeTrainer(tc)

    def mutate(step):
        tr.extra_meta["anneal_step0"] = step

    tr.fit(iter([(np.zeros((4, 8), np.float32),) for _ in range(4)]),
           steps=4, log=lambda *a: None, on_step=mutate)
    assert tr.ckpt.saves == [2, 4]
    assert [m.get("anneal_step0") for m in tr.ckpt.metas] == [2, 4]


def test_sigterm_handler_is_idempotent_and_rearmable(tmp_path):
    tc = TrainConfig(checkpoint_dir=str(tmp_path), batch_size=4,
                     preflight_checkpoint=False)
    tr = FakeTrainer(tc)
    tr.install_preemption_handler(log=lambda *a: None)
    os.kill(os.getpid(), signal.SIGTERM)
    os.kill(os.getpid(), signal.SIGTERM)       # second latch: no effect
    assert tr._preempt and tr._signal_save
    tr.install_preemption_handler(log=lambda *a: None)   # re-arm for reuse
    assert not tr._preempt and not tr.preempted


# ---------------------------------------------------------------------------
# elastic runtime units (membership, heartbeats, the agent over jax-free
# python children)
# ---------------------------------------------------------------------------

def test_epoch_file_roundtrip_and_process_ids(tmp_path):
    ef = elastic.EpochFile(str(tmp_path))
    assert ef.read() is None
    ep = ef.write(elastic.Epoch(epoch=3, members=[0, 2], port=12345))
    got = ef.read()
    assert got == ep
    assert got.nproc == 2 and got.coordinator_address == "127.0.0.1:12345"
    assert got.process_id(2) == 1 and got.process_id(1) is None


def test_heartbeat_write_read_stale_and_throttle(tmp_path):
    d = str(tmp_path)
    hb = elastic.Heartbeat(d, 0, interval_s=30.0)
    assert hb.beat(step=5, epoch=1)
    assert not hb.beat(step=6)                  # throttled
    assert hb.beat(step=6, force=True)
    beats = elastic.read_heartbeats(d)
    assert beats[0]["step"] == 6 and beats[0]["pid"] == os.getpid()
    now = time.time()
    assert elastic.stale_workers(d, [0, 1], 5.0, now=now) == [1]  # missing
    assert elastic.stale_workers(d, [0], 5.0, now=now + 60) == [0]


def test_hung_workers_progress_and_age_semantics(tmp_path):
    """hung = provably wedged: fresh beat with a frozen step (live beater,
    hung main thread) or a stale existing file (frozen process). A missing
    file or a fresh setup-phase beat (no step yet — first-step compile) is
    NOT hung."""
    d = str(tmp_path)
    now = 1000.0

    def write(wid, t, step, step_time):
        with open(os.path.join(d, f"hb_{wid}.json"), "w") as fh:
            json.dump({"worker_id": wid, "pid": 1, "time": t,
                       "step": step, "step_time": step_time}, fh)

    write(0, now - 0.1, 7, now - 0.2)        # healthy: advancing
    write(1, now - 0.1, 5, now - 10.0)       # hung main thread
    write(2, now - 10.0, 3, now - 10.0)      # frozen process
    write(3, now - 0.1, None, None)          # still compiling/restoring
    assert elastic.hung_workers(d, [0, 1, 2, 3, 4], 2.0, now=now) == [1, 2]
    # stale_workers keeps missing-as-stale (agent bootstrap semantics)
    assert 4 in elastic.stale_workers(d, [0, 4], 2.0, now=now)


def test_worker_beater_keeps_file_fresh_while_main_thread_sleeps(tmp_path):
    ep = elastic.Epoch(epoch=0, members=[0], port=1)
    w = elastic.ElasticWorker(str(tmp_path), 0, ep, hb_interval_s=0.05)
    w.start()
    try:
        w.on_step(1)
        t = elastic.read_heartbeats(str(tmp_path))[0]["time"]
        time.sleep(0.3)       # main thread idle: the beater must publish
        doc = elastic.read_heartbeats(str(tmp_path))[0]
        assert doc["time"] > t
        assert doc["step"] == 1       # progress unchanged, presence fresh
    finally:
        w.stop()


def test_heartbeat_injected_fault_absorbed_by_retry(tmp_path):
    chaos.install(FaultPlan([Fault(kind="fail_io", site="heartbeat",
                                   times=1)]))
    hb = elastic.Heartbeat(str(tmp_path), 1, interval_s=0.0)
    assert hb.beat(step=1, force=True)          # retried through the fault
    assert counters()['retry.attempts_total{op="heartbeat"}'] == 1
    assert elastic.read_heartbeats(str(tmp_path))[1]["step"] == 1


def test_on_step_survives_heartbeat_outage_past_the_budget(tmp_path):
    """A heartbeat outage longer than the retry budget must not kill the
    training loop it reports on — the stale file IS the failure signal."""
    logs = []
    ep = elastic.Epoch(epoch=0, members=[0], port=1)
    w = elastic.ElasticWorker(str(tmp_path), 0, ep, log=logs.append)
    chaos.install(FaultPlan([Fault(kind="fail_io", site="heartbeat",
                                   times=99)]))
    w.on_step(3)                                # must not raise
    assert any("heartbeat beat failed" in l for l in logs)


# -- agent over tiny jax-free children ---------------------------------------

CHILD = """
import json, os, sys, time
run_dir, wid = sys.argv[1], sys.argv[2]
mode = sys.argv[3]
marker = os.path.join(run_dir, f"crashed_{wid}")
ep = json.load(open(os.path.join(run_dir, "epoch.json")))
def beat():
    p = os.path.join(run_dir, f"hb_{wid}.json")
    tmp = p + ".tmp"
    json.dump({"worker_id": int(wid), "pid": os.getpid(),
               "time": time.time()}, open(tmp, "w"))
    os.replace(tmp, p)
beat()
if mode == "crash_once" and wid == "1" and not os.path.exists(marker):
    open(marker, "w").close()
    sys.exit(1)
if mode == "crash_always" and wid == "1":
    sys.exit(1)
if mode == "reconfigure_once" and wid == "1" and not os.path.exists(marker):
    open(marker, "w").close()
    sys.exit(77)
if mode == "hang" and wid == "1" and not os.path.exists(marker):
    open(marker, "w").close()
    time.sleep(600)
for _ in range(3):
    beat(); time.sleep(0.05)
sys.exit(0)
"""


def _agent(tmp_path, mode, **kw):
    run_dir = str(tmp_path / "pod")
    os.makedirs(run_dir, exist_ok=True)
    script = tmp_path / "child.py"
    script.write_text(CHILD)

    def spawn(worker_id, epoch):
        return subprocess.Popen(
            [sys.executable, str(script), run_dir, str(worker_id), mode])

    return elastic.ElasticAgent(run_dir, spawn, members=[0, 1],
                                poll_s=0.05, term_grace_s=2.0, **kw)


def test_agent_respawns_crashed_worker_and_completes(tmp_path):
    agent = _agent(tmp_path, "crash_once")
    events = agent.run(deadline_s=60)
    kinds = [e["kind"] for e in events]
    assert kinds.count("epoch_start") == 2
    assert any(e["kind"] == "worker_lost" and e["worker"] == 1
               for e in events)
    assert agent.epoch.members == [0, 1]        # respawn keeps the slot
    assert kinds[-1] == "pod_done"


def test_agent_shrinks_around_a_dead_worker(tmp_path):
    agent = _agent(tmp_path, "crash_always", policy="shrink",
                   max_reconfigures=2)
    events = agent.run(deadline_s=60)
    assert agent.epoch.members == [0]           # reshaped to the survivor
    assert agent.reconfigures == 1
    assert [e["kind"] for e in events][-1] == "pod_done"


def test_agent_exit_reconfigure_worker_rejoins_even_under_shrink(tmp_path):
    agent = _agent(tmp_path, "reconfigure_once", policy="shrink")
    agent.run(deadline_s=60)
    # exit 77 is a reshape REQUEST, not a death: the worker keeps its slot
    assert agent.epoch.members == [0, 1]
    assert agent.reconfigures == 1


def test_agent_detects_hang_via_heartbeat_staleness(tmp_path):
    agent = _agent(tmp_path, "hang", hb_timeout_s=1.0)
    events = agent.run(deadline_s=60)
    assert any(e["kind"] == "worker_hung" and e["worker"] == 1
               for e in events)
    assert [e["kind"] for e in events][-1] == "pod_done"


def test_agent_gives_up_on_crash_loop(tmp_path):
    agent = _agent(tmp_path, "crash_always", policy="respawn",
                   max_reconfigures=2)
    with pytest.raises(RuntimeError, match="crash loop"):
        agent.run(deadline_s=60)


# ---------------------------------------------------------------------------
# slow tier: the real multi-process recovery scenarios via chaos_smoke
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_chaos_smoke_kill_respawn_bitwise(tmp_path):
    """The acceptance scenario end to end: SIGKILL a worker mid-step in a
    real 2-process gloo/DCN run; recovery must be bitwise-identical to the
    uninterrupted reference (scripts/chaos_smoke.py asserts it; this runs
    the real CLI)."""
    r = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__), "..",
                                      "scripts", "chaos_smoke.py"),
         "--outdir", str(tmp_path), "--scenarios", "kill_respawn"],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, timeout=900)
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-2000:]
    summary = json.load(open(tmp_path / "summary.json"))
    assert summary["ok"] and summary["scenarios"]["kill_respawn"]
