"""End-to-end "rainbow" integration test — the framework's equivalent of the
reference's executable-notebook validation (examples/rainbow_dalle.ipynb,
SURVEY.md §4): synthetic shape images → train the dVAE → train DALL·E on the
dVAE codes → autoregressively generate → **token-exact accuracy** against the
dVAE's own encoding (notebook cells 23-44: train accuracy ≈ 1.0).

Sized for the 8-device CPU mesh (~90 s): 16px shapes, 16-code dVAE, 2-layer
DALLE, full overfit on 32 samples."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_tpu.config import (DVAEConfig, DalleConfig, MeshConfig, OptimConfig,
                              TrainConfig)
from dalle_tpu.data.loaders import Token
from dalle_tpu.data.synthetic import ShapesDataset
from dalle_tpu.models.dalle import DALLE
from dalle_tpu.models.wrapper import DalleWithVae, DiscreteVAEAdapter
from dalle_tpu.train.trainer_dalle import DalleTrainer
from dalle_tpu.train.trainer_vae import VAETrainer


@pytest.mark.slow
def test_rainbow_end_to_end(tmp_path):
    ds = ShapesDataset(image_size=16)
    idx = list(range(0, len(ds), max(1, len(ds) // 32)))[:32]
    imgs = np.stack([ds[i].image for i in idx]).astype(np.float32) / 255.0
    caps = [ds[i].caption for i in idx]

    # --- stage 1: dVAE (notebook cells 23-30) -----------------------------
    vcfg = DVAEConfig(image_size=16, num_tokens=16, codebook_dim=16,
                      num_layers=2, hidden_dim=16, num_resnet_blocks=1)
    tc = TrainConfig(batch_size=32, checkpoint_dir=str(tmp_path / "v"),
                     log_every=10 ** 6, preflight_checkpoint=False,
                     mesh=MeshConfig(dp=8), metrics_every=20,
                     optim=OptimConfig(learning_rate=3e-3, grad_clip_norm=0.0))
    vt = VAETrainer(vcfg, tc)
    first = None
    for _ in range(200):
        m = vt.train_step(imgs)
        if m and first is None:
            first = m["loss"]
    assert m["loss"] < first * 0.5, "dVAE recon must improve substantially"

    vae = DiscreteVAEAdapter(vt.model, vt.state.params)
    codes = np.asarray(vae.get_codebook_indices(imgs))
    assert codes.shape == (32, 16)
    # hard reconstructions stay in a sane pixel range
    recons = np.asarray(vae.decode(jnp.asarray(codes)))
    assert np.isfinite(recons).all()

    # --- stage 2: DALLE on word-level Token captions (cells 31-40) --------
    tok = Token([c.split() for c in caps])
    text = tok.parse(seq_len=8)
    dcfg = DalleConfig(num_text_tokens=tok.num_pairs, text_seq_len=8, dim=64,
                       depth=2, heads=2, dim_head=16, image_size=16,
                       image_vocab_size=16, image_fmap_size=4)
    tc2 = TrainConfig(batch_size=32, checkpoint_dir=str(tmp_path / "d"),
                      log_every=10 ** 6, preflight_checkpoint=False,
                      mesh=MeshConfig(dp=8), metrics_every=50,
                      optim=OptimConfig(learning_rate=2e-3, grad_clip_norm=0.0))
    dt = DalleTrainer(dcfg, tc2)
    for _ in range(300):
        m = dt.train_step(text, codes)
    assert m["loss_img"] < 0.05, f"DALLE must overfit the codes, got {m}"

    # --- stage 3: generation + token-exact accuracy (cells 41-44) ---------
    ids = dt.model.apply(dt.state.params, jnp.asarray(text[:8]),
                         jax.random.PRNGKey(0), filter_thres=0.9,
                         temperature=0.5, method=DALLE.generate_images_tokens)
    acc = float((np.asarray(ids) == codes[:8]).mean())
    assert acc > 0.8, f"train token-exact accuracy {acc:.3f} (chance 0.0625)"

    # --- decode fast paths on the TRAINED model (VERDICT r3 weak #2):
    # bf16 / int8-KV / int8-weights must hold token-exact accuracy within a
    # couple of points of f32 — untrained-model agreement says nothing (near-
    # uniform logits flip argmax under any noise); this is the case users run
    from dalle_tpu.ops.quantize_weights import quantize_params_int8
    from dalle_tpu.train.train_state import cast_floating

    bf16 = cast_floating(dt.state.params, jnp.bfloat16)
    for name, p, cache_dtype in [
            ("bf16", bf16, jnp.bfloat16),
            ("bf16_int8kv", bf16, jnp.int8),
            ("int8w_int8kv", quantize_params_int8(dt.state.params), jnp.int8)]:
        ids_q = dt.model.apply(p, jnp.asarray(text[:8]), jax.random.PRNGKey(0),
                               filter_thres=0.9, temperature=0.5,
                               cache_dtype=cache_dtype,
                               method=DALLE.generate_images_tokens)
        acc_q = float((np.asarray(ids_q) == codes[:8]).mean())
        assert acc_q > acc - 0.05, (
            f"{name} decode degraded on trained model: {acc_q:.3f} vs "
            f"f32 {acc:.3f}")

    # decoded images come back in range through the full wrapper
    dv = DalleWithVae(dt.model, dt.state.params, vae)
    out = dv.generate_images(jnp.asarray(text[:2]), jax.random.PRNGKey(1),
                             temperature=0.5, filter_thres=0.9)
    assert out.shape == (2, 16, 16, 3) and bool(jnp.isfinite(out).all())


@pytest.mark.slow
def test_rainbow_heldout_generalization(tmp_path):
    """The reference notebook's VALIDATION capability (VERDICT r4 #2,
    rainbow_dalle.ipynb cells 23-44): train DALL·E on a 30% split of the
    compositional shapes set and measure token-exact accuracy on the 70% of
    caption combinations it never saw. Reference numbers: train ≈ 1.0,
    held-out ≈ 0.3, per-position > 0.8. This framework's full-scale run
    (examples/rainbow_dalle.py defaults, 1×v5e, r5) measured train 0.833 /
    held-out 0.750 token-exact — recorded in NEXT.md. In-suite scale is
    trimmed for the CPU mesh; the band asserts generalization is far above
    the chance floor (1/num_tokens), not the full-scale numbers."""
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "examples"))
    from rainbow_dalle import main as rainbow_main

    metrics = rainbow_main([
        "--image_size", "16", "--num_tokens", "32", "--vae_steps", "220",
        "--dalle_steps", "450", "--dim", "96", "--depth", "3",
        "--train_frac", "0.3", "--outdir", str(tmp_path)])
    chance = 1.0 / 32
    assert metrics["train_exact"] > 0.5, metrics
    assert metrics["held-out_exact"] > 6 * chance, metrics   # ≫ chance floor
    assert metrics["held-out_pos_frac"] >= 0.1, metrics
