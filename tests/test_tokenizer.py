"""Text tokenizer tests — the L1 layer (SURVEY.md §2.3).

The golden ids below were produced by the reference SimpleTokenizer
(dalle_pytorch/tokenizer.py:55-152) over the shipped CLIP merges vocabulary;
the default tokenizer must reproduce them exactly (vocab 49,408).
"""

import numpy as np
import pytest

from dalle_tpu.text.bpe import BPE, DEFAULT_VOCAB_PATH, load_merges, train_bpe
from dalle_tpu.text.tokenizer import SimpleTokenizer, YttmTokenizer, get_tokenizer

# (text, reference token ids) — reference tokenizer.py encode() outputs
GOLDEN = [
    ("a cloudy sky at sunset", [320, 13106, 2390, 536, 3424]),
    ("Hello, World! 123", [3306, 267, 1002, 256, 272, 273, 274]),
    ("the quick brown fox jumps over the lazy dog.",
     [518, 3712, 2866, 3240, 18911, 962, 518, 10753, 1929, 269]),
    ("an oil painting of a fox's tail - impressionism",
     [550, 2870, 3086, 539, 320, 3240, 568, 4132, 268, 36114]),
    ("unicode text with emoji \U0001F308 mixed in",
     [7648, 19639, 4160, 593, 16327, 13042, 6780, 530]),
    ("supercalifragilisticexpialidocious antidisestablishmentarianism",
     [1642, 2857, 13093, 2076, 5868, 26850, 835, 639, 38466, 3120, 4262,
      7726, 12658, 1585, 44351]),
    ("A RAINBOW-colored umbrella;   with    weird whitespace",
     [320, 6286, 268, 11775, 17143, 282, 593, 5613, 4699, 2138]),
]


@pytest.fixture(scope="module")
def tok():
    return SimpleTokenizer()


class TestDefaultVocab:
    def test_vocab_file_ships(self):
        assert DEFAULT_VOCAB_PATH.exists()

    def test_default_vocab_size_is_clip(self, tok):
        # 256 bytes + 256 byte+'</w>' + 48,894 merges + 2 specials
        assert tok.vocab_size == 49408

    @pytest.mark.parametrize("text,ids", GOLDEN, ids=[t[:20] for t, _ in GOLDEN])
    def test_reference_golden_ids(self, tok, text, ids):
        assert tok.encode(text) == ids

    @pytest.mark.parametrize("text,ids", GOLDEN, ids=[t[:20] for t, _ in GOLDEN])
    def test_round_trip(self, tok, text, ids):
        # decode emits one space per word-token (same as the reference: every
        # '</w>' becomes ' '), so punctuation comes back space-separated and
        # text lowercased/whitespace-collapsed
        from dalle_tpu.text.bpe import WORD_PAT, clean_text
        expect = " ".join(WORD_PAT.findall(clean_text(text)))
        assert tok.decode(tok.encode(text)) == expect

    def test_tokenize_contract(self, tok):
        out = tok.tokenize(["a cloudy sky at sunset", "hello"],
                           context_length=16)
        assert out.shape == (2, 16) and out.dtype == np.int32
        assert out[0, :5].tolist() == GOLDEN[0][1]
        assert (out[0, 5:] == 0).all() and (out[1, 1:] == 0).all()

    def test_tokenize_truncation(self, tok):
        long = "painting " * 64
        with pytest.raises(RuntimeError):
            tok.tokenize(long, context_length=8)
        out = tok.tokenize(long, context_length=8, truncate_text=True)
        assert out.shape == (1, 8) and (out != 0).all()


class TestByteLevelFallback:
    def test_explicit_empty_merges_gives_byte_level(self):
        t = SimpleTokenizer(bpe_path=None, merges=[])
        assert t.vocab_size == 514
        assert t.decode(t.encode("hello world")) == "hello world"


class TestMergesIO:
    def test_gz_and_plain_load_identically(self, tmp_path):
        import gzip
        merges = load_merges(DEFAULT_VOCAB_PATH, limit=100)
        plain = tmp_path / "m.txt"
        plain.write_text("#version: test\n" +
                         "\n".join(f"{a} {b}" for a, b in merges))
        assert load_merges(plain) == merges

    def test_clip_header_is_skipped(self):
        merges = load_merges(DEFAULT_VOCAB_PATH, limit=3)
        # first real merge of the CLIP vocab is 'i n' (file line 2)
        assert merges[0] == ("i", "n")


class TestTrainFlow:
    def test_train_save_load(self, tmp_path):
        corpus = ["red square blue circle"] * 50 + ["green triangle"] * 30
        path = tmp_path / "learned.txt"
        t = SimpleTokenizer.train(corpus, num_merges=32, save_path=str(path))
        assert t.vocab_size == 514 + len(t.bpe.merges)
        reloaded = YttmTokenizer(str(path))
        assert reloaded.encode("red square") == t.encode("red square")

    def test_get_tokenizer_registry_default(self):
        t = get_tokenizer("simple")
        assert t.vocab_size == 49408


class TestChineseTokenizer:
    def test_local_vocab_file(self, tmp_path):
        """ChineseTokenizer from a local WordPiece vocab (the offline path —
        no hub access in this environment)."""
        pytest.importorskip("transformers")
        vocab = tmp_path / "vocab.txt"
        vocab.write_text("\n".join(
            ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
             "你", "好", "世", "界", "猫", "红", "色"]) + "\n")
        from dalle_tpu.text.tokenizer import ChineseTokenizer
        tok = ChineseTokenizer(str(vocab))
        assert tok.vocab_size == 12
        ids = tok.encode("你好世界")
        assert ids == [5, 6, 7, 8]
        out = tok.tokenize(["红色猫"], context_length=8)
        assert out.shape == (1, 8) and out.dtype == np.int32
        assert out[0, :3].tolist() == [10, 11, 9]
        assert "你 好" in tok.decode(ids) or "你好" in tok.decode(ids)

    def test_default_falls_back_to_vendored_vocab(self, monkeypatch):
        """get_tokenizer('chinese') must be executable offline: the default
        hub model falls back to the vendored mini WordPiece vocab
        (text/data/chinese_vocab_mini.txt) with a warning (VERDICT r2 #8).
        from_pretrained is stubbed to raise OSError — env-var tricks
        (HF_HUB_OFFLINE) bind at transformers import time and would not
        force the branch on a machine with the model cached."""
        transformers = pytest.importorskip("transformers")
        from dalle_tpu.text.tokenizer import ChineseTokenizer, get_tokenizer
        assert ChineseTokenizer.VENDORED_VOCAB.is_file()

        def unreachable(*a, **k):
            raise OSError("hub unreachable (test stub)")

        monkeypatch.setattr(transformers.BertTokenizer, "from_pretrained",
                            unreachable)
        with pytest.warns(UserWarning, match="vendored mini vocab"):
            tok = get_tokenizer("chinese")
        assert tok.vocab_size >= 150
        ids = tok.encode("红色圆形")
        assert len(ids) == 4 and all(i > 4 for i in ids)   # no [UNK] (id 1)
        round_trip = tok.decode(ids).replace(" ", "")
        assert round_trip == "红色圆形"
