"""DalleTrainer + driver entry points on the 8-device CPU mesh."""

import math
import pathlib
import sys

import jax
import numpy as np
import pytest

from dalle_tpu.config import (DalleConfig, MeshConfig, OptimConfig,
                              PrecisionConfig, TrainConfig)
from dalle_tpu.parallel.mesh import build_mesh
from dalle_tpu.train.trainer_dalle import DalleTrainer

# recompilation budget (conftest guard): ceiling = the module's cold
# full-run TOTAL (427 measured post-jit_step-sharing: the equal-config
# trainer pairs in the scan/resume tests now ride the first test's compiled
# step — 2-4 compiles each instead of a full re-jit) + ~15% slack for
# cross-jax-version compile-count variance; the total bounds any single
# test standalone in any order/subset. Exceeding it means new compilation
# work — see docs/LINT.md.
pytestmark = pytest.mark.recompile_budget(490)

TINY = DalleConfig(num_text_tokens=32, text_seq_len=8, dim=32, depth=2, heads=2,
                   dim_head=16, image_size=16, image_vocab_size=32,
                   image_fmap_size=4)


def _batch(rng, cfg, n):
    text = rng.randint(1, cfg.num_text_tokens, (n, cfg.text_seq_len))
    ids = rng.randint(0, cfg.image_vocab_size, (n, cfg.image_seq_len))
    return text, ids


def test_train_step_decreases_loss(tmp_path, rng):
    mesh_cfg = MeshConfig(dp=4, fsdp=2)
    tc = TrainConfig(batch_size=8, checkpoint_dir=str(tmp_path),
                     preflight_checkpoint=False, mesh=mesh_cfg,
                     optim=OptimConfig(learning_rate=1e-2))
    tr = DalleTrainer(TINY, tc, mesh=build_mesh(mesh_cfg))
    text, ids = _batch(rng, TINY, 8)
    losses = [tr.train_step(text, ids)["loss"] for _ in range(12)]
    assert all(math.isfinite(x) for x in losses)
    assert losses[-1] < losses[0]  # memorizes a fixed batch


def test_sharded_step_matches_single_device(tmp_path, rng):
    """DP+TP sharding must not change the math (same seed → same loss)."""
    text, ids = _batch(rng, TINY, 8)
    results = {}
    for name, mesh_cfg in [("multi", MeshConfig(dp=2, fsdp=2, tp=2)),
                           ("single", MeshConfig())]:
        mesh = (build_mesh(mesh_cfg) if name == "multi"
                else build_mesh(mesh_cfg, devices=jax.devices()[:1]))
        # f32 compute: this test checks that sharding does not change the
        # math, so keep precision noise out of the comparison
        tc = TrainConfig(batch_size=8, checkpoint_dir=str(tmp_path / name),
                         preflight_checkpoint=False, mesh=mesh_cfg,
                         precision=PrecisionConfig(compute="float32"))
        tr = DalleTrainer(TINY, tc, mesh=mesh)
        results[name] = [tr.train_step(text, ids)["loss"] for _ in range(3)]
    np.testing.assert_allclose(results["multi"], results["single"],
                               rtol=2e-4)


def test_train_steps_scan_matches_single_steps(tmp_path, rng):
    """k steps in one scanned program ≡ k single-step dispatches (same
    stacked data, rng-free config) — the multi-step path is a dispatch
    optimization, not different math."""
    k, b = 3, 8
    texts = np.stack([_batch(rng, TINY, b)[0] for _ in range(k)])
    rng2 = np.random.RandomState(7)
    idss = np.stack([rng2.randint(0, TINY.image_vocab_size,
                                  (b, TINY.image_seq_len)) for _ in range(k)])
    mesh_cfg = MeshConfig(dp=4, fsdp=2)
    tc = TrainConfig(batch_size=b, checkpoint_dir=str(tmp_path),
                     preflight_checkpoint=False, mesh=mesh_cfg,
                     precision=PrecisionConfig(compute="float32"),
                     optim=OptimConfig(learning_rate=1e-2))

    tr1 = DalleTrainer(TINY, tc, mesh=build_mesh(mesh_cfg))
    single = [tr1.train_step(texts[i], idss[i])["loss"] for i in range(k)]

    tr2 = DalleTrainer(TINY, tc, mesh=build_mesh(mesh_cfg))
    m = tr2.train_steps(texts, idss)
    assert tr2._host_step == k
    np.testing.assert_allclose(m["loss"], single[-1], rtol=1e-5)
    np.testing.assert_allclose(m["loss_mean"], np.mean(single), rtol=1e-5)
    p1 = jax.device_get(tr1.state.params)
    p2 = jax.device_get(tr2.state.params)
    for a, b_ in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
        np.testing.assert_allclose(a, b_, rtol=2e-5, atol=2e-6)


def test_fit_checkpoint_resume(tmp_path, rng):
    mesh_cfg = MeshConfig(dp=2)
    mesh = build_mesh(mesh_cfg, devices=jax.devices()[:2])
    tc = TrainConfig(batch_size=4, checkpoint_dir=str(tmp_path),
                     save_every_steps=5, mesh=mesh_cfg)
    tr = DalleTrainer(TINY, tc, mesh=mesh)
    text, ids = _batch(rng, TINY, 4)
    tr.fit(iter([(text, ids)] * 6), steps=5, log=lambda *a: None)
    assert tr.ckpt.latest_step() == 5

    tr2 = DalleTrainer(TINY, tc, mesh=mesh)
    meta = tr2.restore()
    assert meta["model_class"] == "DALLE"
    assert int(tr2.state.step) == 5
    p1 = jax.tree.leaves(tr.state.params)[0]
    p2 = jax.tree.leaves(tr2.state.params)[0]
    np.testing.assert_allclose(np.asarray(p1), np.asarray(p2))


def test_graft_entry_compiles():
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
    import __graft_entry__ as ge
    fn, args = ge.entry()
    # compile-check only (driver does the same); tiny eval via eval_shape
    out = jax.eval_shape(fn, *args)
    assert out.shape == ()


def test_graft_dryrun_multichip(monkeypatch):
    # the DCN throughput smoke (two extra subprocess fleets) runs in the
    # slow-tier variant below and in the driver's own dryrun invocation
    monkeypatch.setenv("GRAFT_DRYRUN_SKIP_DCN", "1")
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
    import __graft_entry__ as ge
    ge.dryrun_multichip(8)


@pytest.mark.slow
def test_graft_dryrun_multichip_full(monkeypatch):
    monkeypatch.delenv("GRAFT_DRYRUN_SKIP_DCN", raising=False)
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
    import __graft_entry__ as ge
    ge.dryrun_multichip(8)
