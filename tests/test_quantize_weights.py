"""int8 decode weight quantization (ops/quantize_weights.py): QDense must be
bit-identical to nn.Dense in float mode, dequantized matmuls must track the
float results within per-channel quant noise, and the wrapper precision modes
must produce valid samples from the same trained params tree."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_tpu.ops.quantize_weights import (QDense, quantize_kernel_int8,
                                            quantize_params_int8)


def test_qdense_matches_nn_dense_exactly():
    """Same param names, shapes, init stream, and float math — swapping
    nn.Dense for QDense must not change any existing model or checkpoint."""
    x = jnp.asarray(np.random.RandomState(0).rand(4, 16), jnp.float32)
    for use_bias in (True, False):
        a = nn.Dense(8, use_bias=use_bias)
        b = QDense(8, use_bias=use_bias)
        va = a.init(jax.random.PRNGKey(7), x)
        vb = b.init(jax.random.PRNGKey(7), x)
        for (ka, la), (kb, lb) in zip(
                sorted(jax.tree_util.tree_flatten_with_path(va)[0],
                       key=str),
                sorted(jax.tree_util.tree_flatten_with_path(vb)[0],
                       key=str)):
            assert str(ka) == str(kb)
            np.testing.assert_array_equal(la, lb)
        np.testing.assert_array_equal(a.apply(va, x), b.apply(vb, x))


def test_quantize_kernel_roundtrip_error_bounded():
    rng = np.random.RandomState(1)
    w = jnp.asarray(rng.standard_normal((64, 32)) * 0.1, jnp.float32)
    q, scale = quantize_kernel_int8(w, axis=0)
    assert q.dtype == jnp.int8 and scale.shape == (1, 32)
    deq = q.astype(jnp.float32) * scale
    # symmetric per-channel int8: error ≤ scale/2 per element
    assert float(jnp.max(jnp.abs(deq - w) / scale)) <= 0.5 + 1e-6


def test_qdense_int8_close_to_float():
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.rand(4, 32), jnp.float32)
    m = QDense(16)
    v = m.init(jax.random.PRNGKey(0), x)
    out_f = m.apply(v, x)
    qv = quantize_params_int8(v, compute_dtype=None)
    out_q = m.apply(qv, x)
    # relative error bounded by int8 resolution over the contraction
    err = float(jnp.max(jnp.abs(out_f - out_q)))
    ref = float(jnp.max(jnp.abs(out_f)))
    assert err < 0.02 * max(ref, 1.0), (err, ref)


def test_quantize_params_does_not_mutate_source():
    x = jnp.ones((2, 8))
    m = QDense(4)
    v = m.init(jax.random.PRNGKey(0), x)
    before = np.asarray(v["params"]["kernel"]).copy()
    qv = quantize_params_int8(v)
    assert qv["params"]["kernel"].dtype == jnp.int8
    np.testing.assert_array_equal(v["params"]["kernel"], before)
    assert v["params"]["kernel"].dtype == jnp.float32


def test_zero_variance_rows_produce_safe_nonzero_scales():
    """All-zero and all-equal channels: amax hits 0 (or one value) and a
    naive amax/127 scale would be 0 — dividing by it NaNs the whole
    kernel. The 1e-8 floor must keep every scale strictly positive, the
    roundtrip finite, and exact values exactly representable."""
    w = np.zeros((64, 8), np.float32)
    w[:, 1] = 0.25                       # zero-variance nonzero channel
    w[:, 2] = -3.0
    q, scale = quantize_kernel_int8(jnp.asarray(w), axis=0)
    scale = np.asarray(scale)
    assert (scale > 0).all()             # the floor, not a zero scale
    deq = np.asarray(q, np.float32) * scale
    assert np.isfinite(deq).all()
    np.testing.assert_array_equal(deq[:, 0], 0.0)          # zeros exact
    np.testing.assert_allclose(deq[:, 1], 0.25, rtol=1e-6)  # ±127 exact
    np.testing.assert_allclose(deq[:, 2], -3.0, rtol=1e-6)
    # row-wise (shared_emb) flavor of the same edge
    q, scale = quantize_kernel_int8(jnp.zeros((4, 16)), axis=1)
    assert (np.asarray(scale) > 0).all() and scale.shape == (4, 1)
    np.testing.assert_array_equal(np.asarray(q), 0)


@pytest.mark.parametrize("shape", [(100, 37), (7, 129), (130, 128)])
def test_non_multiple_dims_roundtrip_error_bounded(shape):
    """Vocab/feature dims off the 128-lane grid (ragged tokenizers, odd
    heads) must quantize with the same per-element error bound as aligned
    shapes — no padding assumption hides in the math."""
    rng = np.random.RandomState(3)
    w = jnp.asarray(rng.standard_normal(shape) * 0.2, jnp.float32)
    for axis in (0, 1):
        q, scale = quantize_kernel_int8(w, axis=axis)
        want = [1, 1]
        want[1 - axis] = shape[1 - axis]
        assert scale.shape == tuple(want)
        deq = q.astype(jnp.float32) * scale
        # symmetric rounding: error ≤ scale/2 per element, every element
        assert float(jnp.max(jnp.abs(deq - w) / scale)) <= 0.5 + 1e-6


def test_qdense_non_multiple_features_end_to_end():
    x = jnp.asarray(np.random.RandomState(4).rand(3, 37), jnp.float32)
    m = QDense(29)
    v = m.init(jax.random.PRNGKey(0), x)
    out_f = m.apply(v, x)
    qv = quantize_params_int8(v, compute_dtype=None)
    assert qv["params"]["kernel"].shape == (37, 29)
    out_q = m.apply(qv, x)
    err = float(jnp.max(jnp.abs(out_f - out_q)))
    assert err < 0.02 * max(float(jnp.max(jnp.abs(out_f))), 1.0)


def test_qdense_int8_without_scales_raises():
    x = jnp.ones((2, 8))
    m = QDense(4)
    v = m.init(jax.random.PRNGKey(0), x)
    v2 = {"params": {"kernel": jnp.zeros((8, 4), jnp.int8),
                     "bias": v["params"]["bias"]}}
    with pytest.raises(ValueError, match="quant"):
        m.apply(v2, x)


@pytest.mark.parametrize("share", [False, True])
def test_dalle_int8w_decode_runs(share):
    """End-to-end: quantized variables drive the full cached decode loop
    (prefill + nn.scan) in both head modes (tied table / Dense head)."""
    from dalle_tpu.config import DalleConfig
    from dalle_tpu.models.dalle import DALLE, init_dalle

    cfg = DalleConfig(num_text_tokens=32, text_seq_len=8, dim=32, depth=2,
                      heads=2, dim_head=16, image_size=16,
                      image_vocab_size=32, image_fmap_size=4,
                      share_input_output_emb=share)
    model, params = init_dalle(cfg, jax.random.PRNGKey(0))
    text = jnp.asarray(np.random.RandomState(0).randint(1, 32, (2, 8)))
    qv = quantize_params_int8(params)
    assert "quant" in qv
    ids = model.apply(qv, text, jax.random.PRNGKey(1), filter_thres=0.9,
                      cache_dtype=jnp.int8,
                      method=DALLE.generate_images_tokens)
    assert ids.shape == (2, 16) and ids.dtype == jnp.int32
    assert bool((ids >= 0).all()) and bool((ids < 32).all())


def test_wrapper_int8w_precision_mode():
    from dalle_tpu.config import DalleConfig, DVAEConfig
    from dalle_tpu.models.dvae import init_dvae
    from dalle_tpu.models.dalle import init_dalle
    from dalle_tpu.models.wrapper import DalleWithVae, DiscreteVAEAdapter

    vcfg = DVAEConfig(image_size=16, num_tokens=32, codebook_dim=16,
                      num_layers=2, hidden_dim=8, num_resnet_blocks=0)
    vmodel, vparams = init_dvae(vcfg, jax.random.PRNGKey(0))
    vae = DiscreteVAEAdapter(vmodel, vparams)
    dcfg = DalleConfig(num_text_tokens=32, text_seq_len=8, dim=32, depth=2,
                      heads=2, dim_head=16, image_size=16,
                      image_vocab_size=32, image_fmap_size=4)
    model, params = init_dalle(dcfg, jax.random.PRNGKey(1))
    dv = DalleWithVae(model, params, vae)
    text = jnp.asarray(np.random.RandomState(0).randint(1, 32, (2, 8)))
    out = dv.generate_images(text, jax.random.PRNGKey(2), precision="int8w")
    assert out.shape == (2, 16, 16, 3) and bool(jnp.isfinite(out).all())
    # per-mode cache: alternating modes must not re-derive either tree
    out2 = dv.generate_images(text, jax.random.PRNGKey(2), precision="bf16",
                              topk_approx=True)
    assert set(dv._fast_params[1]) == {"int8w", "bf16"}
    tree_int8w = dv._fast_params[1]["int8w"]
    dv.generate_images(text, jax.random.PRNGKey(2), precision="int8w")
    assert dv._fast_params[1]["int8w"] is tree_int8w
    assert out2.shape == (2, 16, 16, 3)
