"""Test harness: force an 8-device CPU platform so every mesh/sharding path is
exercised without TPU hardware (SURVEY.md §4: the reference's only multi-node
test mechanism was the no-op DummyBackend; we get real SPMD on virtual devices).

Must run before jax initializes — pytest imports conftest first, so setting the
env here is safe as long as no test module imports jax at collection time before
this file (pytest guarantees conftest loads first).
"""

import os

# Force CPU even when the outer environment points at a TPU (JAX_PLATFORMS=axon):
# unit tests must exercise the 8-device virtual mesh, and host CPU compiles are
# much faster than the tunneled chip for tiny shapes. NOTE: the image's
# sitecustomize imports jax at interpreter startup, so env vars are too late —
# but backends initialize lazily, so jax.config.update still wins as long as no
# plugin has created a client yet.
#
# DALLE_TPU_TESTS=1 keeps the real accelerator instead, enabling the
# TPU-gated tests (e.g. Mosaic compilation of the pallas kernels in
# test_flash_attention.py) — the rest of the suite still passes but runs
# slower through the device tunnel.
_USE_REAL_TPU = os.environ.get("DALLE_TPU_TESTS") == "1"
if not _USE_REAL_TPU:
    os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import numpy as np  # noqa: E402
import pytest  # noqa: E402

if not _USE_REAL_TPU:
    jax.config.update("jax_platforms", "cpu")

jax.config.update("jax_default_matmul_precision", "float32")

# ---------------------------------------------------------------------------
# recompilation guard (dalle_tpu/analysis/recompile_guard.py): the listener
# must be installed before any test compiles, so it lives here. Tests (or
# whole modules, via ``pytestmark``) declare a per-test ceiling with
# ``@pytest.mark.recompile_budget(N)``; exceeding it fails the test even when
# its assertions pass — recompile drift only shows up as wall-clock on
# hardware, which is exactly where it is most expensive to discover.
# ---------------------------------------------------------------------------
from dalle_tpu.analysis.recompile_guard import install_compile_counter  # noqa: E402

_COMPILE_COUNTER = install_compile_counter()


@pytest.fixture(autouse=True)
def _recompile_budget(request):
    marker = request.node.get_closest_marker("recompile_budget")
    report = os.environ.get("GRAFTLINT_RECOMPILE_REPORT") == "1"
    if marker is None and not report:
        yield
        return
    if marker is not None and not (
            marker.args and isinstance(marker.args[0], int)):
        pytest.fail("recompile_budget marker requires an integer ceiling, "
                    "e.g. @pytest.mark.recompile_budget(40)", pytrace=False)
    start = _COMPILE_COUNTER.count
    yield
    used = _COMPILE_COUNTER.count - start
    if report:
        print(f"\n[recompile] {request.node.nodeid}: {used} backend compiles")
    if marker is not None and used > marker.args[0]:
        pytest.fail(
            f"recompilation budget exceeded: {used} XLA backend compiles > "
            f"declared ceiling {marker.args[0]} for {request.node.nodeid}. "
            "Ceilings are set to the module's cold full-run TOTAL, which "
            "bounds any single test in any order — so this is new "
            "compilation work: look for fresh static args, unhashable "
            "statics, or shape churn (graftlint's jit-static-hazard rule "
            "catches the common causes). Raise the marker only if the new "
            "compiles are intentional.", pytrace=False)


@pytest.fixture(scope="session")
def devices():
    return jax.devices()


@pytest.fixture(scope="session")
def mesh8():
    from dalle_tpu.config import MeshConfig
    from dalle_tpu.parallel.mesh import build_mesh
    return build_mesh(MeshConfig(dp=2, fsdp=2, tp=2, sp=1))


@pytest.fixture
def rng():
    return np.random.RandomState(0)
