"""Permuter round-trips, minGPT forward/cached-sample equivalence, and the
Net2Net conditional transformer (taming second-stage parity)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_tpu.config import VQGANConfig
from dalle_tpu.models.cond_transformer import (CoordStage, Net2NetTransformer,
                                               SOSProvider)
from dalle_tpu.models.mingpt import GPT, GPTConfig, init_gpt, make_sampler
from dalle_tpu.models.vqgan import init_vqgan
from dalle_tpu.ops.permuter import PERMUTERS, make_permuter
from dalle_tpu.utils.misc import kmeans


class TestPermuters:
    @pytest.mark.parametrize("kind", sorted(PERMUTERS))
    def test_round_trip(self, kind):
        # the reference's own self-test: p(p(x), reverse=True) == x
        # (taming permuter.py:236-248)
        p = make_permuter(kind, 8, 8)
        x = np.arange(2 * 64).reshape(2, 64)
        assert np.array_equal(p(p(x), reverse=True), x)
        assert np.array_equal(p(p(x, reverse=True)), x)

    def test_zcurve_visits_quadrants_hierarchically(self):
        p = make_permuter("zcurve", 4, 4)
        # first 4 tokens of a 4×4 z-curve are the top-left 2×2 block
        first4 = set(p.idx[:4].tolist())
        assert first4 == {0, 1, 4, 5}

    def test_alternate_parsing_boustrophedon(self):
        p = make_permuter("alternate_parsing", 2, 3)
        assert p.idx.tolist() == [0, 1, 2, 5, 4, 3]

    def test_embedding_axis_permute(self):
        p = make_permuter("random", 4, 4)
        x = np.random.RandomState(0).rand(2, 16, 8)
        assert np.allclose(p(p(x, axis=-2), reverse=True, axis=-2), x)


GPT_SMALL = GPTConfig(vocab_size=64, block_size=32, n_layer=2, n_head=2,
                      n_embd=32)


@pytest.fixture(scope="module")
def gpt():
    return init_gpt(GPT_SMALL, jax.random.PRNGKey(0), batch=2)


class TestMinGPT:
    def test_forward_shape(self, gpt):
        model, params = gpt
        idx = jnp.zeros((2, 8), jnp.int32)
        logits = model.apply(params, idx)
        assert logits.shape == (2, 8, 64)

    def test_causality(self, gpt):
        model, params = gpt
        idx = jnp.zeros((1, 8), jnp.int32)
        base = model.apply(params, idx)
        # changing a future token must not affect past logits
        idx2 = idx.at[0, 5].set(3)
        pert = model.apply(params, idx2)
        assert jnp.allclose(base[0, :5], pert[0, :5], atol=1e-5)
        assert not jnp.allclose(base[0, 5:], pert[0, 5:], atol=1e-5)

    def test_prepended_embeddings(self, gpt):
        model, params = gpt
        idx = jnp.zeros((2, 4), jnp.int32)
        emb = jnp.ones((2, 3, 32)) * 0.1
        logits = model.apply(params, idx, embeddings=emb)
        assert logits.shape == (2, 7, 64)

    def test_n_unmasked_prefix_sees_future(self):
        cfg = GPT_SMALL.replace(n_unmasked=4)
        model, params = init_gpt(cfg, jax.random.PRNGKey(1), batch=1)
        idx = jnp.zeros((1, 8), jnp.int32)
        base = model.apply(params, idx)
        # a change inside the unmasked prefix affects ALL positions
        pert = model.apply(params, idx.at[0, 2].set(7))
        assert not jnp.allclose(base[0, 0], pert[0, 0], atol=1e-6)

    def test_cached_decode_matches_full_forward(self, gpt):
        model, params = gpt
        idx = jnp.array([[1, 2, 3, 4, 5, 6]], jnp.int32)
        full = model.apply(params, idx)
        cache = model.init_cache(1)
        logits, cache, n0 = model.apply(params, idx[:, :3], cache,
                                        method=GPT.prefill)
        assert jnp.allclose(logits, full[0, 2], atol=1e-4)
        for t in range(3, 6):
            logits, cache = model.apply(params, idx[:, t:t + 1], t, cache,
                                        method=GPT.decode_one)
            assert jnp.allclose(logits[0], full[0, t], atol=1e-4), f"pos {t}"

    def test_sampler_runs_and_respects_prompt(self, gpt):
        model, params = gpt
        sampler = make_sampler(model, steps=5, top_k=8)
        prompt = jnp.array([[3, 1, 4]], jnp.int32)
        out = sampler(params, prompt, jax.random.PRNGKey(0))
        assert out.shape == (1, 8)
        assert jnp.array_equal(out[:, :3], prompt)
        assert ((out >= 0) & (out < 64)).all()


class TestCoordStage:
    def test_encode_decode(self):
        cs = CoordStage(n_embed=16, down_factor=2)
        c = jnp.linspace(0, 1, 1 * 8 * 8).reshape(1, 8, 8, 1)
        quant, ids = cs.encode(c)
        assert quant.shape == (1, 4, 4, 1)
        assert ids.shape == (1, 16)
        assert ids.max() <= 15  # clamped to n_embed-1 bins
        dec = cs.decode(quant)
        assert dec.shape == (1, 8, 8, 1)

    def test_sos_provider(self):
        sp = SOSProvider(sos_token=5)
        _, ids = sp.encode(jnp.zeros((3, 4, 4, 1)))
        assert ids.shape == (3, 1) and (ids == 5).all()


VQ_TINY = VQGANConfig(embed_dim=8, n_embed=32, z_channels=8, resolution=16,
                      ch=8, ch_mult=(1, 2), num_res_blocks=1,
                      attn_resolutions=(8,))


class TestNet2Net:
    @pytest.fixture(scope="class")
    def n2n(self):
        vq_model, vq_params = init_vqgan(VQ_TINY, jax.random.PRNGKey(0))
        # 8×8 latents = 64 z tokens; cond = coord stage on 16px maps → 64 tokens
        cs = CoordStage(n_embed=15, down_factor=2)
        gpt_cfg = GPTConfig(vocab_size=48, block_size=192, n_layer=2, n_head=2,
                            n_embd=32)
        n2n = Net2NetTransformer.from_vqgan(
            gpt_cfg, vq_model, vq_params, cond_encode=cs.encode,
            permuter=make_permuter("zcurve", 8, 8), pkeep=0.9)
        gpt_params = n2n.gpt.init(jax.random.PRNGKey(1),
                                  jnp.zeros((1, 4), jnp.int32))
        return n2n, gpt_params

    def test_forward_shapes_and_targets(self, n2n):
        model, gpt_params = n2n
        x = jnp.ones((2, 16, 16, 3)) * 0.1
        c = jnp.linspace(0, 1, 2 * 16 * 16).reshape(2, 16, 16, 1)
        logits, target = model.forward(gpt_params, x, c,
                                       key=jax.random.PRNGKey(2), train=True)
        assert target.shape == (2, 64)          # 8×8 first-stage codes
        assert logits.shape == (2, 64, 48)      # one prediction per z position
        loss = model.loss(gpt_params, x, c, key=jax.random.PRNGKey(3))
        assert jnp.isfinite(loss)

    def test_pkeep_zero_randomizes_inputs_not_targets(self, n2n):
        model, gpt_params = n2n
        model.pkeep = 0.0
        x = jnp.ones((1, 16, 16, 3)) * 0.1
        c = jnp.zeros((1, 16, 16, 1))
        _, t1 = model.forward(gpt_params, x, c, key=jax.random.PRNGKey(1))
        _, t2 = model.forward(gpt_params, x, c, key=jax.random.PRNGKey(2))
        model.pkeep = 0.9
        assert jnp.array_equal(t1, t2), "targets are the true codes, unmasked"

    def test_sample_decodes_images(self, n2n):
        model, gpt_params = n2n
        c = jnp.linspace(0, 1, 1 * 16 * 16).reshape(1, 16, 16, 1)
        imgs = model.sample(gpt_params, c, steps=64, key=jax.random.PRNGKey(0),
                            top_k=8)
        assert imgs.shape == (1, 16, 16, 3)
        assert bool(jnp.isfinite(imgs).all())


def test_kmeans_clusters():
    rng = np.random.RandomState(0)
    a = rng.randn(50, 3) + np.array([5, 0, 0])
    b = rng.randn(50, 3) + np.array([-5, 0, 0])
    pts = np.concatenate([a, b])
    cents, assign = kmeans(pts, 2, iters=10)
    assert cents.shape == (2, 3)
    # the two blobs separate
    assert len(set(np.asarray(assign[:50]).tolist())) == 1
    assert assign[0] != assign[50]
