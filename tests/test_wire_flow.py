"""graftwire — the static wire-protocol model, its rules, the golden
protocol-contract workflow, and the runtime frame tap.

Fixture style mirrors test_sync_flow.py: small synthetic sources fed
through ``build_model`` (keyed on the REAL endpoint-map paths/qualnames so
the curated ENDPOINTS specs apply), plus repo-level invariants (the tree
stays wire-clean; the committed golden matches the live model) and the two
injected-drift acceptance cases: a new field on the health reply must
produce a drift line naming the verb, the field and both endpoint sites,
and an unmapped ``record_event`` must be an
undeclared-lifecycle-transition finding.
"""

import json
import os
import socket
import subprocess
import sys
import textwrap

from dalle_tpu.analysis import rules_wire, wire_flow
from dalle_tpu.analysis.wire_flow import (
    build_model, build_repo_model, lifecycle_cycles,
)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN = os.path.join(ROOT, "contracts", "wire.json")

# synthetic sources are parsed AS this file so the curated ENDPOINTS map
# (keyed path::qualname) classifies their sends/reads onto real channels
_TP = "dalle_tpu/fleet/transport.py"


def model_of(src, path=_TP):
    return build_model([(path, textwrap.dedent(src))])


def findings_of(src, rule, path=_TP):
    return [f for f in rules_wire.run_wire(model_of(src, path))
            if f.rule == rule]


def _repo_sources():
    out = {}
    for rel in wire_flow.wire_files(ROOT):
        with open(os.path.join(ROOT, rel), encoding="utf-8") as fh:
            out[rel] = fh.read()
    return out


# ---------------------------------------------------------------------------
# shape extraction: literals, incremental builds, optional spreads, verbs
# ---------------------------------------------------------------------------

WIRE_FIX = """
    class ReplicaServer:
        def _serve_conn(self, conn):
            msg = recv_frame(conn)
            verb = msg.get("verb")
            if verb == "submit":
                self._handle_submit(conn, msg)
            elif verb == "health":
                send_frame(conn, self._health(msg))
            else:
                send_frame(conn, {"error": "unknown_verb", "detail": verb})

        def _health(self, msg):
            h = {"ok": True, "slots": 4,
                 **({"wedged": True} if msg else {})}
            h.update(pid=123)
            h["step"] = 7
            h.setdefault("draining", False)
            return h

        def _submit_kwargs(self, msg):
            d = msg.get("deadline")

        def _handle_submit(self, conn, msg):
            send_frame(conn, {"ok": True, "junk": 1})
            send_frame(conn, {"kind": "row", "row": 0, "tokens": []})
            send_frame(conn, {"kind": "done", "rows": 2})

        def _handle_group(self, conn, msg):
            send_frame(conn, {"ok": True})


    class RemoteReplica:
        def _track_progress(self, h):
            step = h["step"]
            wedged = h["wedged"]
            ok = h.get("ok")

        def _open_stream(self, req, cls):
            ack = recv_frame(self._sock)
            if not ack.get("ok"):
                err = ack.get("missing")


    class RemoteResultStream:
        def events(self):
            frame = recv_frame(self._sock)
            k = frame.get("kind")
            r = frame.get("row")
            t = frame.get("tokens")
            n = frame.get("rows")


    def client_call(addr):
        call(addr, {"verb": "submit", "deadline": 1.0})
        call(addr, {"verb": "teleport"})
    """


def test_incremental_dict_build_and_optional_spread():
    ch = model_of(WIRE_FIX).channels()[("health", "reply", None)]
    # literal + update(kw=) + subscript assign + setdefault all land
    assert ch.sent_fields == {"ok", "slots", "wedged", "pid", "step",
                             "draining"}
    # **({...} if cond else {}) keys are conditionally present
    assert ch.optional_fields == {"wedged"}
    assert not ch.dynamic


def test_verb_requests_and_stream_subchannels():
    channels = model_of(WIRE_FIX).channels()
    assert channels[("submit", "request", None)].sent_fields == \
        {"verb", "deadline"}
    assert channels[("submit", "stream", "row")].sent_fields == \
        {"kind", "row", "tokens"}
    assert channels[("submit", "stream", "done")].sent_fields == \
        {"kind", "rows"}
    # the kind-agnostic reader is fanned onto every concrete sub-channel
    assert "row" in channels[("submit", "stream", "row")].read_fields
    assert "rows" in channels[("submit", "stream", "done")].read_fields


def test_call_fed_dict_is_dynamic():
    src = """
    class ReplicaServer:
        def _telemetry(self, msg):
            body = telemetry_payload(self._tel)
            return body
    """
    ch = model_of(src).channels()[("telemetry", "reply", None)]
    assert ch.dynamic and ch.sent_fields == set()


def test_nested_handler_class_keeps_its_qualname():
    """The gateway's Handler is a class nested inside _make_handler; the
    walker must keep the class segment or the SSE endpoint map misses."""
    model = build_repo_model(ROOT)
    ch = model.channels().get(("sse", "stream", "*"))
    assert ch is not None and ch.senders
    sites = {s.site for s in ch.senders}
    assert ("dalle_tpu/gateway/server.py::_make_handler.Handler._stream"
            in sites)


# ---------------------------------------------------------------------------
# the rules
# ---------------------------------------------------------------------------

def test_unread_field_flagged_with_both_sites():
    found = findings_of(WIRE_FIX, "wire-field-unread")
    assert len(found) == 1
    assert "'junk'" in found[0].message
    assert "submit.reply" in found[0].message
    assert "_handle_submit" in found[0].message       # sender site
    assert "_open_stream" in found[0].message         # mapped receiver


def test_unsourced_read_flagged_once_across_overlapping_channels():
    # ack.get("missing") maps to submit/submit_group/any replies; no
    # sender of ANY of them sets it -> exactly one finding at the read
    found = findings_of(WIRE_FIX, "wire-field-unsourced")
    assert len(found) == 1
    assert "'missing'" in found[0].message
    assert "_open_stream" in found[0].message
    assert "default forever" in found[0].message


def test_sourced_anywhere_suppresses_the_overlap_false_positive():
    # "ok" is set by submit.reply but NOT by any.reply — the shared read
    # must stay clean (the variable holds one message at runtime)
    found = findings_of(WIRE_FIX, "wire-field-unsourced")
    assert all("'ok'" not in f.message for f in found)


def test_hard_read_of_optional_field_flagged():
    found = findings_of(WIRE_FIX, "wire-optional-no-default")
    assert len(found) == 1
    assert "'wedged'" in found[0].message
    assert "health.reply" in found[0].message
    assert "KeyError" in found[0].message
    # the required field read the same way is fine
    assert all("'step'" not in f.message for f in found)


def test_verb_orphans_both_directions():
    found = findings_of(WIRE_FIX, "wire-verb-orphan")
    msgs = "\n".join(f.message for f in found)
    assert len(found) == 2
    assert "'teleport' is sent" in msgs and "unknown_verb" in msgs
    assert "'health' is dispatched" in msgs and "no client" in msgs


def test_unmapped_record_event_flagged():
    src = """
    def _bogus_probe():
        record_event("bogus_event_name", x=1)
    """
    found = findings_of(src, "undeclared-lifecycle-transition")
    assert len(found) == 1
    assert "bogus_event_name" in found[0].message
    assert "_bogus_probe" in found[0].message
    assert "EVENT_EDGES" in found[0].message


def test_event_claiming_undeclared_edge_flagged(monkeypatch):
    monkeypatch.setitem(wire_flow.EVENT_EDGES, "request_completed",
                        (("request", "done", "submitted"),))
    src = """
    def _finish():
        record_event("request_completed")
    """
    found = findings_of(src, "undeclared-lifecycle-transition")
    assert len(found) == 1
    assert "done->submitted" in found[0].message
    assert "does not declare" in found[0].message


def test_lifecycle_cycle_detection():
    assert lifecycle_cycles() == []                  # the shipped machines
    cyc = lifecycle_cycles({"m": {"edges": (("a", "b"), ("b", "a"))}})
    assert len(cyc) == 1 and cyc[0][0] == "m"


# ---------------------------------------------------------------------------
# waivers (through the full audit pipeline on a tmp repo)
# ---------------------------------------------------------------------------

def _tmp_audit(tmp_path, source, update=False):
    mod = tmp_path / "dalle_tpu" / "fleet" / "transport.py"
    mod.parent.mkdir(parents=True, exist_ok=True)
    mod.write_text(textwrap.dedent(source))
    return rules_wire.audit(repo_root=str(tmp_path),
                            contract_path=str(tmp_path / "wire.json"),
                            update=update, paths=[_TP])


def test_waiver_with_reason_suppresses_finding(tmp_path):
    src = WIRE_FIX.replace(
        '            send_frame(conn, {"ok": True, "junk": 1})',
        '            # graftwire: allow=wire-field-unread -- operator '
        'dashboard field, reader lands next PR\n'
        '            send_frame(conn, {"ok": True, "junk": 1})')
    report = _tmp_audit(tmp_path, src)
    assert all(f.rule != "wire-field-unread" for f in report.findings)
    waived_rules = [f.rule for f, _ in report.waived]
    assert waived_rules == ["wire-field-unread"]
    assert "dashboard" in report.waived[0][1]
    assert all("wire-field-unread" not in p for p in report.problems)


def test_waiver_without_reason_is_a_problem(tmp_path):
    src = WIRE_FIX.replace(
        '            send_frame(conn, {"ok": True, "junk": 1})',
        '            # graftwire: allow=wire-field-unread\n'
        '            send_frame(conn, {"ok": True, "junk": 1})')
    report = _tmp_audit(tmp_path, src)
    assert any("has no reason" in p for p in report.problems)
    assert any(f.rule == "wire-field-unread" for f in report.findings)


def test_waiver_with_unknown_rule_is_a_problem(tmp_path):
    src = WIRE_FIX.replace(
        '            send_frame(conn, {"ok": True, "junk": 1})',
        '            # graftwire: allow=wire-feild-unread -- typo\n'
        '            send_frame(conn, {"ok": True, "junk": 1})')
    report = _tmp_audit(tmp_path, src)
    assert any("unknown graftwire rule" in p for p in report.problems)


# ---------------------------------------------------------------------------
# golden protocol-contract workflow
# ---------------------------------------------------------------------------

CLEAN_FIX = """
    class ReplicaServer:
        def _serve_conn(self, conn):
            msg = recv_frame(conn)
            verb = msg.get("verb")
            if verb == "submit":
                pass

        def _handle_submit(self, conn, msg):
            send_frame(conn, {"ok": True})


    class RemoteReplica:
        def _open_stream(self, req, cls):
            ack = recv_frame(self._sock)
            ok = ack.get("ok")


    def client_call(addr):
        call(addr, {"verb": "submit"})
    """


def test_golden_roundtrip_then_drift(tmp_path):
    report = _tmp_audit(tmp_path, CLEAN_FIX, update=True)
    assert report.updated and not report.failed
    assert (tmp_path / "wire.json").exists()

    # unchanged source: clean check, no drift
    report = _tmp_audit(tmp_path, CLEAN_FIX)
    assert not report.failed and not report.missing
    assert report.drift == []

    # a new reply field drifts, named with verb + field + endpoint sites
    report = _tmp_audit(tmp_path, CLEAN_FIX.replace(
        '{"ok": True}', '{"ok": True, "extra": 1}'))
    assert report.failed
    [line] = [d for d in report.drift if d.startswith("+ field")]
    assert line.startswith("+ field submit.reply extra")
    assert "_handle_submit" in line and "_open_stream" in line

    # a removed sender drifts too (the reader keeps the channel alive)
    report = _tmp_audit(tmp_path, CLEAN_FIX.replace(
        'call(addr, {"verb": "submit"})', "pass"))
    assert report.failed
    assert any(d.startswith("- sender submit.request")
               for d in report.drift)


def test_missing_golden_is_distinct_from_drift(tmp_path):
    report = _tmp_audit(tmp_path, CLEAN_FIX)
    assert report.missing and not report.failed


def _run_audit_cli(*args):
    return subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "wire_audit.py"),
         *args],
        cwd=ROOT, capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})


def test_cli_exit_codes_missing_vs_drift(tmp_path):
    # missing golden: the distinct exit 3 (needs --update, not a code fix)
    r = _run_audit_cli("--check", "--contract", str(tmp_path / "nope.json"))
    assert r.returncode == 3, r.stdout + r.stderr
    assert "MISSING" in r.stdout

    # doctored golden (one health-reply field dropped): real drift, exit 1
    golden = json.load(open(GOLDEN))
    fields = golden["verbs"]["health"]["reply"]["sender"]["fields"]
    assert fields, "repo golden has no health-reply fields to doctor"
    doctored_path = tmp_path / "doctored.json"
    doctored_path.write_text(json.dumps(golden))
    fields.pop()
    doctored_path.write_text(json.dumps(golden))
    r = _run_audit_cli("--check", "--contract", str(doctored_path))
    assert r.returncode == 1, r.stdout + r.stderr
    assert "wire-contract drift: + field health.reply" in r.stdout


def test_cli_list_rules():
    r = _run_audit_cli("--list-rules")
    assert r.returncode == 0
    for rule in rules_wire.WIRE_RULES:
        assert rule in r.stdout


# ---------------------------------------------------------------------------
# injected-drift acceptance: a new field on the health reply
# ---------------------------------------------------------------------------

def test_injected_health_field_names_verb_field_and_both_sites():
    files = _repo_sources()
    src = files[_TP]
    anchor = "ok=True, pid=os.getpid(),"
    assert src.count(anchor) == 1, "health-reply builder moved; fix anchor"
    files[_TP] = src.replace(anchor,
                             "ok=True, pid=os.getpid(), extra_field=1,")
    model = build_model(sorted(files.items()))
    drift = rules_wire.diff_contract(json.load(open(GOLDEN)),
                                     rules_wire.wire_contract(model))
    assert len(drift) == 1, drift
    line = drift[0]
    assert line.startswith("+ field health.reply extra_field")
    # both endpoint sites: every sender of the channel, every receiver
    assert "dalle_tpu/fleet/transport.py::ReplicaServer._health" in line
    assert "dalle_tpu/gateway/replica.py::Replica.health" in line
    assert "receiver" in line
    assert "dalle_tpu/fleet/controller.py::FleetController._degraded" in line


def test_injected_undeclared_event_in_a_wire_root():
    files = _repo_sources()
    files[_TP] += ("\n\ndef _bogus_probe():\n"
                   "    record_event(\"bogus_event_name\", x=1)\n")
    model = build_model(sorted(files.items()))
    found = [f for f in rules_wire.run_wire(model)
             if f.rule == "undeclared-lifecycle-transition"]
    assert len(found) == 1
    assert found[0].path == _TP
    assert "bogus_event_name" in found[0].message


# ---------------------------------------------------------------------------
# repo-level invariants
# ---------------------------------------------------------------------------

def test_repo_is_wire_clean():
    """The real wire roots carry no graftwire findings — not even waived
    ones — and match the committed golden: the same invariant ci_local's
    graftwire stage and the ci.yml step enforce."""
    report = rules_wire.audit(repo_root=ROOT, contract_path=GOLDEN)
    msgs = [str(f) for f in report.findings] \
        + [f"waiver-problem: {p}" for p in report.problems] \
        + [f"drift: {d}" for d in report.drift]
    assert not report.missing, "golden contracts/wire.json missing"
    assert not report.failed, "\n".join(msgs)
    assert report.waived == [], "wire roots must carry zero waivers"


def test_golden_is_schema_current_and_acyclic():
    golden = json.load(open(GOLDEN))
    assert golden["schema"] == rules_wire.SCHEMA
    machines = {n: {"edges": [tuple(e) for e in m["edges"]]}
                for n, m in golden["lifecycles"].items()}
    assert lifecycle_cycles(machines) == []
    # every declared edge stays within its machine's state set
    for name, m in golden["lifecycles"].items():
        states = set(m["states"])
        for s, d in m["edges"]:
            assert s in states and d in states


def test_golden_events_reference_declared_edges():
    golden = json.load(open(GOLDEN))
    declared = {f"{name}:{s}->{d}"
                for name, m in golden["lifecycles"].items()
                for s, d in m["edges"]}
    for name, entry in golden["events"].items():
        for edge in entry["edges"]:
            assert edge in declared, f"event {name} claims {edge}"
        assert entry["sites"], f"event {name} has no emission site"


# ---------------------------------------------------------------------------
# runtime frame tap (obs/wiretap.py)
# ---------------------------------------------------------------------------

def test_wiretap_records_real_frames_and_conforms():
    from dalle_tpu.fleet import transport
    from dalle_tpu.obs import wiretap
    golden = json.load(open(GOLDEN))
    req = {f: 1 for f in
           golden["verbs"]["health"]["request"]["sender"]["fields"]}
    req["verb"] = "health"
    wiretap.install()
    try:
        wiretap.reset()
        a, b = socket.socketpair()
        try:
            transport.send_frame(a, req)
            assert transport.recv_frame(b, timeout=2.0) == req
        finally:
            a.close()
            b.close()
        # send and recv of the same frame dedup to one shape
        assert wiretap.observed() == [
            ("health", "request", None, frozenset(req))]
        assert wiretap.conformance(golden) == []
        # a verb outside the contract is a violation
        wiretap._tap("send", {"verb": "teleport"})
        violations = wiretap.conformance(golden)
        assert len(violations) == 1
        assert "teleport" in str(violations[0])
        wiretap.reset()
        assert wiretap.observed() == []
    finally:
        wiretap.uninstall()
    assert transport._frame_tap is None and not wiretap.installed()


SYNTH_GOLDEN = {"verbs": {
    "submit": {
        "reply": {"sender": {"fields": ["ok"], "dynamic": False}},
        "stream": {"row": {"sender": {"fields": ["kind", "row"],
                                      "dynamic": False}}},
    },
    # HTTP-side pseudo-verb: must NOT wildcard-cover transport frames
    "sse": {"stream": {"*": {"sender": {"fields": [], "dynamic": True}}}},
}}


def test_wiretap_classification():
    from dalle_tpu.obs import wiretap
    assert wiretap._classify("send", {"verb": "submit", "deadline": 1}) \
        == ("submit", "request", None, frozenset({"verb", "deadline"}))
    assert wiretap._classify("recv", {"kind": "row", "row": 0}) \
        == (None, "stream", "row", frozenset({"kind", "row"}))
    assert wiretap._classify("recv", {"ok": True}) \
        == (None, "reply", None, frozenset({"ok"}))


def test_wiretap_conformance_violation_kinds():
    from dalle_tpu.obs import wiretap
    wiretap.reset()

    def violations_of(frame):
        wiretap.reset()
        wiretap._tap("send", frame)
        out = wiretap.conformance(SYNTH_GOLDEN)
        wiretap.reset()
        return out

    assert violations_of({"ok": True}) == []                 # reply covered
    assert violations_of({"kind": "row", "row": 0}) == []    # stream covered
    [v] = violations_of({"nope": 1})                         # unknown reply
    assert "reply fields not covered" in v.why
    [v] = violations_of({"kind": "row", "row": 0, "extra": 1})
    assert "stream fields not covered" in v.why
    # the sse "*" dynamic sender is excluded from the tap's view: an
    # unknown stream kind still violates
    [v] = violations_of({"kind": "bogus_kind"})
    assert "not in the golden" in v.why
