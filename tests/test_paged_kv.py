"""Paged-KV decode (graftpage): host control plane (BlockPool refcounts,
RadixCache longest-prefix/COW/LRU-eviction semantics), the PagedKVCache
write/gather/copy device ops, and the engine-level bar — paged serving is
TOKEN-EXACT against the dense engine's sequential references for any
admission order, precision, CFG pairing and pool pressure, with zero
recompiles once the fixed program set is warm."""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_tpu.config import DalleConfig
from dalle_tpu.models.dalle import DALLE, init_dalle
from dalle_tpu.ops.attention import KVCache
from dalle_tpu.ops.decode_attention import (decode_attend_window_kernel,
                                            decode_attend_window_paged)
from dalle_tpu.ops.paged_kv import PagedKVCache
from dalle_tpu.serve import DecodeEngine, RequestQueue
from dalle_tpu.serve.paged import BlockPool, RadixCache

# ceiling = module cold full-run total (measured 440) + ~15% cross-version
# slack (the test_serve convention). Paged engines compile ONE fixed
# program set per config key — a change that compiles per admission
# pattern, per radix-hit shape or per pool layout blows straight through.
pytestmark = pytest.mark.recompile_budget(510)

CFG = dict(num_text_tokens=32, text_seq_len=6, dim=32, depth=2, heads=2,
           dim_head=16, image_size=16, image_vocab_size=24, image_fmap_size=4)

TEXTS = [np.array([3, 4, 5, 0, 0, 0], np.int32),
         np.array([7, 8, 0, 0, 0, 0], np.int32),
         np.array([9, 1, 2, 3, 0, 0], np.int32),
         np.array([5, 5, 0, 0, 0, 0], np.int32),
         np.array([1, 2, 3, 4, 5, 6], np.int32)]


@pytest.fixture(scope="module")
def model_params():
    cfg = DalleConfig(**CFG)
    return init_dalle(cfg, jax.random.PRNGKey(0), batch=2)


@pytest.fixture(scope="module")
def refs100(model_params):
    model, params = model_params
    return {i: _reference(model, params, t, 100 + i)
            for i, t in enumerate(TEXTS)}


def _reference(model, params, text, seed, **kw):
    ids = model.apply(params, jnp.asarray(text[None]),
                      jax.random.PRNGKey(seed),
                      method=DALLE.generate_images_tokens, **kw)
    return np.asarray(ids[0])


# ---------------------------------------------------------------------------
# BlockPool (host, no jax)
# ---------------------------------------------------------------------------

def test_pool_alloc_exhaustion_and_refcounts():
    pool = BlockPool(3)
    a, b, c = pool.alloc(), pool.alloc(), pool.alloc()
    assert sorted([a, b, c]) == [0, 1, 2]
    assert pool.alloc() is None                 # dry, caller must evict
    assert (pool.free_count, pool.used_count) == (0, 3)
    pool.retain(a)
    assert pool.shared_count == 1               # only a has >= 2 holders
    pool.release(a)
    assert pool.free_count == 0                 # still held once
    pool.release(a)
    assert pool.free_count == 1                 # refcount 0 -> freed
    assert pool.alloc() == a                    # and reusable


def test_pool_release_of_free_block_asserts():
    pool = BlockPool(1)
    bid = pool.alloc()
    pool.release(bid)
    with pytest.raises(AssertionError):
        pool.release(bid)
    with pytest.raises(AssertionError):
        pool.retain(bid)                        # retain needs a live holder


# ---------------------------------------------------------------------------
# RadixCache (host, no jax)
# ---------------------------------------------------------------------------

def _pooled(n):
    pool = BlockPool(n)
    return pool, RadixCache(block_tokens=4, pool=pool)


def test_radix_miss_partial_and_full_hit():
    pool, rx = _pooled(8)
    key = (1, 2, 3, 4, 5, 6, 7)                 # 1 full block + tail (5,6,7)
    m0 = rx.match(key)
    assert m0.blocks == [] and m0.tail_block is None and m0.hit_tokens == 0
    b0, bt = pool.alloc(), pool.alloc()
    rx.insert(key, [b0], bt)
    assert rx.resident_nodes == 2
    assert pool.refcount(b0) == 2 and pool.refcount(bt) == 2
    full = rx.match(key)                        # exact prompt seen before
    assert full.full and full.blocks == [b0] and full.tail_block == bt
    assert full.hit_tokens == 7
    part = rx.match((1, 2, 3, 4, 9, 9, 9))      # shares the full block only
    assert not part.full and part.blocks == [b0] and part.hit_tokens == 4
    miss = rx.match((8, 2, 3, 4, 5, 6, 7))      # diverges inside block 0
    assert miss.blocks == [] and miss.hit_tokens == 0
    assert (rx.lookups, rx.full_hits, rx.partial_hits) == (4, 1, 1)
    assert rx.hit_tokens_total == 11


def test_radix_block_aligned_prompt_forks_last_full_block():
    pool, rx = _pooled(8)
    key = (1, 2, 3, 4, 5, 6, 7, 8)              # exactly 2 blocks, no tail
    b0, b1 = pool.alloc(), pool.alloc()
    rx.insert(key, [b0, b1], None)
    m = rx.match(key)
    assert m.full and m.blocks == [b0, b1]
    assert m.tail_block == b1                   # COW source = last full block
    assert m.hit_tokens == 8


def test_radix_insert_keeps_incumbent_blocks():
    pool, rx = _pooled(8)
    key = (1, 2, 3, 4, 5)
    b0, bt = pool.alloc(), pool.alloc()
    rx.insert(key, [b0], bt)
    dup_full, dup_tail = pool.alloc(), pool.alloc()
    rx.insert(key, [dup_full], dup_tail)        # re-prefill of a known prompt
    assert rx.resident_nodes == 2               # nothing added
    assert rx.match(key).blocks == [b0]         # incumbent wins
    assert pool.refcount(dup_full) == 1         # caller's copy stays private
    assert pool.refcount(dup_tail) == 1


def test_radix_evicts_lru_leaves_only_at_refcount_zero():
    """Eviction reclaims LRU leaves whose sole holder is the tree itself —
    a block any live row still maps (pool refcount >= 2) is untouchable."""
    pool, rx = _pooled(8)
    old = (1, 2, 3, 4, 5)
    hot = (6, 7, 8, 9, 1)
    ob, ot = pool.alloc(), pool.alloc()
    rx.insert(old, [ob], ot)
    hb, ht = pool.alloc(), pool.alloc()
    rx.insert(hot, [hb], ht)
    for bid in (ob, ot, hb):                    # rows drained: tree-only refs
        pool.release(bid)
    rx.match(hot)                               # hot is most recently used
    rx.match(old)
    rx.match(hot)
    # ht keeps the caller's ref: a live row still maps hot's tail. Leaves
    # are ot (evictable) and ht (pinned); ob/hb are interior until then.
    assert rx.evictable_count() == 1
    freed = rx.evict(10)                        # ot, then ob becomes a leaf
    assert freed == 2 and rx.evictions == 2
    assert rx.resident_nodes == 2               # hot's chain survives
    assert pool.refcount(ht) == 2               # untouched
    assert pool.refcount(ot) == 0 and pool.refcount(ob) == 0
    pool.release(ht)                            # the row completes
    assert rx.evict(10) == 2                    # ht, then hb
    assert pool.free_count == 8 and rx.resident_nodes == 0


def test_radix_eviction_order_is_lru():
    """Two evictable tails: the least-recently-matched one goes first."""
    pool, rx = _pooled(8)
    a, b = (1, 1, 1, 1, 9), (2, 2, 2, 2, 9)
    ab, at = pool.alloc(), pool.alloc()
    rx.insert(a, [ab], at)
    bb, bt = pool.alloc(), pool.alloc()
    rx.insert(b, [bb], bt)
    for bid in (ab, at, bb, bt):
        pool.release(bid)
    rx.match(a)                                 # a is now more recent than b
    assert rx.evict(1) == 1
    assert pool.refcount(bt) == 0               # b's tail was the LRU leaf
    assert pool.refcount(at) == 1               # a's untouched


def test_radix_eviction_parents_follow_leaves():
    pool, rx = _pooled(8)
    deep = (1, 2, 3, 4, 5, 6, 7, 8)             # two chained full blocks
    b0, b1 = pool.alloc(), pool.alloc()
    rx.insert(deep, [b0, b1], None)
    pool.release(b0)
    pool.release(b1)
    rx.match(deep)
    # the interior node (b0) only becomes evictable once its child goes
    assert rx.evictable_count() == 1
    assert rx.evict(1) == 1
    assert pool.refcount(b1) == 0 and pool.refcount(b0) == 1
    assert rx.evictable_count() == 1            # b0 is a leaf now
    assert rx.evict(1) == 1
    assert pool.free_count == 8


# ---------------------------------------------------------------------------
# PagedKVCache ops: write/gather round-trip, COW copy (f32 + int8)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("dtype", [jnp.float32, jnp.int8])
def test_paged_cache_write_gather_matches_dense(dtype):
    """Identical rows written through the page table and into a dense slab
    gather back bitwise equal — the whole exactness argument in one op
    test (unmapped positions gather as the dense slab's zeros)."""
    h, d, bt, max_seq, b = 2, 8, 4, 12, 2
    paged = PagedKVCache.init(num_blocks=6, block_tokens=bt, heads=h,
                              max_seq=max_seq, dim_head=d, dtype=dtype)
    # row 0 maps blocks [5, 1, 3]; row 1 maps [0, 2] (third block unmapped)
    pages = jnp.asarray([[5, 1, 3], [0, 2, -1]], jnp.int32)
    paged = paged.replace(pages=pages)
    dense = KVCache.init(batch=b, heads=h, max_seq=max_seq, dim_head=d,
                         dtype=dtype)

    key = jax.random.PRNGKey(0)
    w = 5
    k_new = jax.random.normal(key, (b, h, w, d), jnp.float32)
    v_new = jax.random.normal(jax.random.fold_in(key, 1), (b, h, w, d))
    offsets = jnp.asarray([0, 3], jnp.int32)
    paged = paged.append_rows(k_new, v_new, offsets)
    dense = dense.append_rows(k_new, v_new, offsets)
    got = paged.gather_dense()
    np.testing.assert_array_equal(np.asarray(got.kv), np.asarray(dense.kv))
    if dtype == jnp.int8:
        np.testing.assert_array_equal(np.asarray(got.scale),
                                      np.asarray(dense.scale))

    # park-offset writes (offset == max_seq) drop for both layouts
    parked = paged.append_rows(k_new[:, :, :1], v_new[:, :, :1],
                               jnp.asarray([max_seq, max_seq], jnp.int32))
    np.testing.assert_array_equal(np.asarray(parked.gather_dense().kv),
                                  np.asarray(got.kv))


def test_paged_cache_copy_blocks_forks_and_drops_oob():
    h, d, bt = 1, 4, 2
    paged = PagedKVCache.init(num_blocks=4, block_tokens=bt, heads=h,
                              max_seq=8, dim_head=d, dtype=jnp.int8)
    pool = jnp.arange(4 * bt * 2 * h * d, dtype=jnp.int8).reshape(
        4, bt, 2 * h * d)
    scale = jnp.arange(4 * bt * 2 * h, dtype=jnp.float32).reshape(
        4, bt, 2 * h)
    paged = paged.replace(pool=pool, scale=scale)
    # fork block 1 -> 3; inactive lane targets an OOB dst (dropped)
    out = paged.copy_blocks(jnp.asarray([1, 0], jnp.int32),
                            jnp.asarray([3, 4], jnp.int32))
    np.testing.assert_array_equal(np.asarray(out.pool[3]),
                                  np.asarray(pool[1]))
    np.testing.assert_array_equal(np.asarray(out.scale[3]),
                                  np.asarray(scale[1]))   # scales ride along
    np.testing.assert_array_equal(np.asarray(out.pool[:3]),
                                  np.asarray(pool[:3]))   # src untouched


def test_paged_attend_matches_dense_kernel():
    """decode_attend_window_paged == the dense windowed kernel on the same
    logical content — the page table is a gather operand, not math."""
    h, d, bt, max_seq, b = 2, 8, 4, 8, 2
    key = jax.random.PRNGKey(7)
    k_new = jax.random.normal(key, (b, h, max_seq, d), jnp.float32)
    v_new = jax.random.normal(jax.random.fold_in(key, 1),
                              (b, h, max_seq, d))
    q = jax.random.normal(jax.random.fold_in(key, 2), (b, h, 1, d))
    dense = KVCache.init(batch=b, heads=h, max_seq=max_seq, dim_head=d,
                         dtype=jnp.float32)
    dense = dense.append_rows(k_new, v_new, jnp.zeros((b,), jnp.int32))
    paged = PagedKVCache.init(num_blocks=4, block_tokens=bt, heads=h,
                              max_seq=max_seq, dim_head=d, dtype=jnp.float32)
    paged = paged.replace(pages=jnp.asarray([[2, 0], [3, 1]], jnp.int32))
    paged = paged.append_rows(k_new, v_new, jnp.zeros((b,), jnp.int32))
    starts = jnp.asarray([0, 0], jnp.int32)
    ref = decode_attend_window_kernel(q, dense, starts)
    got = decode_attend_window_paged(q, paged, starts)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


# ---------------------------------------------------------------------------
# engine: paged serving is token-exact vs the sequential reference
# ---------------------------------------------------------------------------

def test_paged_engine_exact_bulk_admission(model_params, refs100):
    model, params = model_params
    q = RequestQueue()
    for i, t in enumerate(TEXTS):
        q.submit(t, seed=100 + i, request_id=i)
    q.close()
    eng = DecodeEngine(model, params, slots=2, kv_block_tokens=4)
    done = eng.run(q)
    assert sorted(c.request_id for c in done) == list(range(5))
    for c in done:
        np.testing.assert_array_equal(c.tokens, refs100[c.request_id])
    assert eng.stats.occupancy_while_queued == 1.0   # still work-conserving
    st = eng.kv_stats()
    assert st["paged"] and st["block_tokens"] == 4
    # drained: rows released every block; only radix residents stay mapped
    assert st["pages_used"] == st["radix_nodes"]


def test_paged_engine_exact_reversed_and_trickle(model_params, refs100):
    """Admission order must not matter: reversed submission, plus a
    threaded producer trickling requests into freed slots mid-decode."""
    model, params = model_params
    q = RequestQueue()
    by_id = {}
    for i, t in reversed(list(enumerate(TEXTS))):
        by_id[q.submit(t, seed=100 + i).request_id] = i
    q.close()
    eng = DecodeEngine(model, params, slots=2, kv_block_tokens=4)
    for c in eng.run(q):
        np.testing.assert_array_equal(c.tokens, refs100[by_id[c.request_id]])

    q2 = RequestQueue()
    q2.submit(TEXTS[0], seed=100, request_id=0)

    def producer():
        for i in range(1, 5):
            time.sleep(0.01)
            q2.submit(TEXTS[i], seed=100 + i, request_id=i)
        q2.close()

    t = threading.Thread(target=producer)
    t.start()
    eng2 = DecodeEngine(model, params, slots=2, kv_block_tokens=4)
    done = eng2.run(q2)
    t.join()
    assert sorted(c.request_id for c in done) == list(range(5))
    for c in done:
        np.testing.assert_array_equal(c.tokens, refs100[c.request_id])


def test_paged_radix_hits_stay_exact_and_are_counted(model_params, refs100):
    """Duplicate prompts later in the queue land as radix hits — mapped
    blocks + a COW fork instead of a fresh prefill — and their tokens are
    still bitwise the independent single-request generation."""
    model, params = model_params
    dup_ref = _reference(model, params, TEXTS[0], 777)
    q = RequestQueue()
    for i, t in enumerate(TEXTS[:3]):
        q.submit(t, seed=100 + i, request_id=i)
    q.submit(TEXTS[0], seed=777, request_id=3)       # exact repeat: full hit
    # shares TEXTS[2]'s first block (9,1,2,3 prefix after remap differs in
    # the tail only when block boundaries align — worst case it's a miss,
    # the assertion below only pins the REPEAT's full hit)
    q.close()
    eng = DecodeEngine(model, params, slots=2, kv_block_tokens=4)
    done = eng.run(q)
    for c in done:
        ref = dup_ref if c.request_id == 3 else refs100[c.request_id]
        np.testing.assert_array_equal(c.tokens, ref)
    assert eng.stats.radix_full_hits >= 1
    assert eng.stats.cow_forks >= 1                  # full hit forks the tail
    assert eng.stats.prefix_hit_tokens >= 7          # whole prompt mapped
    st = eng.kv_stats()
    assert st["radix_lookups"] == 4
    assert st["prefix_hit_tokens"] == eng.stats.prefix_hit_tokens


def test_paged_engine_int8_kv_exact(model_params):
    """int8w default serving mode (quantized params + int8 KV pages): the
    paged scale planes ride the blocks, dequant is bitwise the dense path."""
    from dalle_tpu.ops.quantize_weights import quantize_params_int8
    model, params = model_params
    qv = quantize_params_int8(params)
    refs = {i: _reference(model, qv, t, 40 + i, cache_dtype=jnp.int8)
            for i, t in enumerate(TEXTS[:3])}
    q = RequestQueue()
    for i, t in enumerate(TEXTS[:3]):
        q.submit(t, seed=40 + i, request_id=i)
    q.submit(TEXTS[1], seed=55, request_id=3)        # int8 radix hit + COW
    q.close()
    ref3 = _reference(model, qv, TEXTS[1], 55, cache_dtype=jnp.int8)
    eng = DecodeEngine(model, qv, slots=2, cache_dtype=jnp.int8,
                       kv_block_tokens=4)
    for c in eng.run(q):
        np.testing.assert_array_equal(
            c.tokens, ref3 if c.request_id == 3 else refs[c.request_id])
    assert eng.stats.radix_full_hits >= 1


def test_paged_cfg_pair_exact(model_params):
    """cond_scale != 1 admits as a cond/uncond pair sharing prompt blocks:
    tokens equal sequential classifier-free guidance bitwise, and the pair
    shows up in the sharing ledger."""
    model, params = model_params
    refs = {i: _reference(model, params, t, 30 + i, cond_scale=2.0)
            for i, t in enumerate(TEXTS[:2])}
    plain = _reference(model, params, TEXTS[2], 99)
    q = RequestQueue()
    for i, t in enumerate(TEXTS[:2]):
        q.submit(t, seed=30 + i, request_id=i, cond_scale=2.0)
    q.submit(TEXTS[2], seed=99, request_id=2)        # unguided neighbor
    q.close()
    eng = DecodeEngine(model, params, slots=4, kv_block_tokens=4)
    done = eng.run(q)
    assert sorted(c.request_id for c in done) == [0, 1, 2]
    for c in done:
        ref = plain if c.request_id == 2 else refs[c.request_id]
        np.testing.assert_array_equal(c.tokens, ref)


def test_paged_eviction_under_pool_pressure_stays_exact(model_params,
                                                        refs100):
    """Minimum legal pool (one CFG-pair admission unit): radix residents
    must be LRU-evicted to admit each next wave — outputs unchanged."""
    model, params = model_params
    eng = DecodeEngine(model, params, slots=2, kv_block_tokens=4,
                       kv_pool_blocks=12)            # 2 slots x 6 blocks
    q = RequestQueue()
    for i, t in enumerate(TEXTS):
        q.submit(t, seed=100 + i, request_id=i)
    q.close()
    done = eng.run(q)
    for c in done:
        np.testing.assert_array_equal(c.tokens, refs100[c.request_id])
    assert eng.stats.pages_evicted > 0               # pressure was real
    st = eng.kv_stats()
    assert st["pages_used"] == st["radix_nodes"]     # drained: rows released


def test_paged_pool_must_fit_one_admission_unit(model_params):
    model, params = model_params
    with pytest.raises(ValueError, match="admission unit"):
        DecodeEngine(model, params, slots=2, kv_block_tokens=4,
                     kv_pool_blocks=11)
    with pytest.raises(ValueError, match="mutually exclusive"):
        DecodeEngine(model, params, slots=2, kv_block_tokens=4,
                     prefill_chunk=3)


def test_paged_no_recompiles_after_warmup(model_params):
    """The no-recompile invariant at test granularity: once one paged run
    has warmed the fixed program set, a second run with a DIFFERENT
    admission pattern, radix-hit mix and pool layout compiles nothing."""
    from dalle_tpu.analysis.recompile_guard import install_compile_counter
    model, params = model_params
    counter = install_compile_counter()
    eng = DecodeEngine(model, params, slots=2, kv_block_tokens=4)
    q = RequestQueue()
    for i, t in enumerate(TEXTS[:3]):
        q.submit(t, seed=100 + i, request_id=i)
    q.close()
    eng.run(q)
    before = counter.count
    q2 = RequestQueue()
    q2.submit(TEXTS[3], seed=103, request_id=0)
    q2.submit(TEXTS[0], seed=500, request_id=1)      # radix full hit + COW
    q2.submit(TEXTS[4], seed=104, request_id=2)
    q2.close()
    eng.run(q2)
    assert counter.count == before, (
        "paged admission recompiled: the page table leaked into a program "
        "signature (shape or static), breaking the fixed-program contract")
