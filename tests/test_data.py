"""Data layer: tar-shard WebDataset pipeline (expansion, streaming, decode,
error skipping, shuffle/batch, prefetch, per-host split, round-trip writer)
and fork loaders (ImageFolder, filename labels, Token vocab, ImagePaths)."""

import io
import os
import tarfile

import numpy as np
import pytest

from dalle_tpu.data.loaders import (ImageFolderDataset, ImagePaths, Token,
                                    batch_arrays, load_labels)
from dalle_tpu.data.webdataset import (WebDataset, decode_sample,
                                       expand_shards, iter_tar_samples,
                                       reraise, split_shards_per_host,
                                       warn_and_continue, write_shards)


def _png_bytes(color, size=8):
    from PIL import Image
    img = Image.new("RGB", (size, size), color)
    buf = io.BytesIO()
    img.save(buf, format="PNG")
    return buf.getvalue()


def _make_shards(tmp_path, n_shards=2, per_shard=4):
    def gen():
        for i in range(n_shards * per_shard):
            yield {"__key__": f"sample{i:04d}",
                   "png": _png_bytes((i * 10 % 255, 0, 0)),
                   "txt": f"caption {i}"}
    return write_shards(gen(), str(tmp_path / "shard-{:03d}.tar"),
                        samples_per_shard=per_shard)


class TestShardExpansion:
    def test_brace_range(self):
        out = expand_shards("s3-{000..003}.tar")
        assert out == ["s3-000.tar", "s3-001.tar", "s3-002.tar", "s3-003.tar"]

    def test_directory_and_glob(self, tmp_path):
        paths = _make_shards(tmp_path)
        assert expand_shards(str(tmp_path)) == sorted(paths)
        assert expand_shards(str(tmp_path / "*.tar")) == sorted(paths)

    def test_pipe_passthrough(self):
        assert expand_shards("pipe:curl -s http://x/a.tar") == \
            ["pipe:curl -s http://x/a.tar"]

    def test_per_host_split_disjoint(self):
        shards = [f"s{i}" for i in range(10)]
        a = split_shards_per_host(shards, 0, 3)
        b = split_shards_per_host(shards, 1, 3)
        c = split_shards_per_host(shards, 2, 3)
        assert sorted(a + b + c) == shards
        assert not (set(a) & set(b))


class TestTarStreaming:
    def test_round_trip_and_grouping(self, tmp_path):
        paths = _make_shards(tmp_path, n_shards=1, per_shard=3)
        samples = list(iter_tar_samples(paths[0], reraise))
        assert len(samples) == 3
        assert samples[0]["__key__"] == "sample0000"
        assert set(samples[0]) == {"__key__", "png", "txt"}

    def test_decode(self, tmp_path):
        paths = _make_shards(tmp_path, n_shards=1, per_shard=1)
        s = decode_sample(next(iter_tar_samples(paths[0], reraise)),
                          image_size=16)
        assert s["png"].shape == (16, 16, 3)
        assert s["png"].dtype == np.float32
        assert s["txt"] == "caption 0"

    def test_corrupt_shard_skipped_with_handler(self, tmp_path):
        bad = tmp_path / "bad.tar"
        bad.write_bytes(b"this is not a tar file at all....")
        assert list(iter_tar_samples(str(bad), warn_and_continue)) == []
        with pytest.raises(Exception):
            list(iter_tar_samples(str(bad), reraise))

    def test_pipe_source(self, tmp_path):
        paths = _make_shards(tmp_path, n_shards=1, per_shard=2)
        out = list(iter_tar_samples(f"pipe:cat {paths[0]}", reraise))
        assert len(out) == 2


class TestPipeline:
    def test_full_chain_batches(self, tmp_path):
        _make_shards(tmp_path, n_shards=2, per_shard=4)
        ds = (WebDataset(str(tmp_path), split_by_host=False)
              .decode(image_size=8)
              .to_tuple("txt", "png")
              .shuffle(4)
              .batched(4))
        batches = list(ds)
        assert len(batches) == 2
        txts, imgs = batches[0]
        assert imgs.shape == (4, 8, 8, 3)
        assert len(txts) == 4

    def test_map_and_select(self, tmp_path):
        _make_shards(tmp_path, n_shards=1, per_shard=4)
        ds = (WebDataset(str(tmp_path), split_by_host=False)
              .decode()
              .select(lambda s: s["__key__"].endswith(("0", "2")))
              .map(lambda s: s["txt"]))
        assert list(ds) == ["caption 0", "caption 2"]

    def test_corrupt_sample_does_not_kill_stream(self, tmp_path):
        # shard with one valid and one corrupt image member
        path = tmp_path / "mix.tar"
        with tarfile.open(path, "w") as tf:
            for key, data in (("a", _png_bytes((1, 2, 3))), ("b", b"NOTPNG")):
                info = tarfile.TarInfo(f"{key}.png")
                info.size = len(data)
                tf.addfile(info, io.BytesIO(data))
        ds = WebDataset(str(path), split_by_host=False).decode()
        out = list(ds)
        assert len(out) == 1 and out[0]["__key__"] == "a"

    def test_prefetch_yields_same_items(self, tmp_path):
        _make_shards(tmp_path, n_shards=2, per_shard=4)
        ds = WebDataset(str(tmp_path), split_by_host=False).decode().map(
            lambda s: s["__key__"])
        direct = list(ds)
        prefetched = list(ds.prefetch(max_queue=2))
        assert sorted(direct) == sorted(prefetched)
        assert len(direct) == 8

    def test_repeat_streams_again(self, tmp_path):
        _make_shards(tmp_path, n_shards=1, per_shard=2)
        ds = WebDataset(str(tmp_path), split_by_host=False, repeat=True).map(
            lambda s: s["__key__"])
        it = iter(ds)
        seen = [next(it) for _ in range(5)]
        assert len(seen) == 5  # wrapped past the 2-sample epoch


@pytest.fixture
def image_folder(tmp_path):
    from PIL import Image
    for cls in ("cat", "dog"):
        d = tmp_path / cls
        d.mkdir()
        for i in range(2):
            Image.new("RGB", (20, 12), (i * 50, 0, 0)).save(
                d / f"{cls}_red_{i}.png")
    return tmp_path


class TestForkLoaders:
    def test_image_folder_classes(self, image_folder):
        ds = ImageFolderDataset(str(image_folder), image_size=8)
        assert len(ds) == 4
        img, cls = ds[0]
        assert img.shape == (8, 8, 3) and img.dtype == np.float32
        assert ds.class_to_idx == {"cat": 0, "dog": 1}
        imgs, clss = batch_arrays(ds, [0, 1, 2, 3])
        assert imgs.shape == (4, 8, 8, 3)
        assert sorted(clss.tolist()) == [0, 0, 1, 1]

    def test_load_labels_from_filenames(self, image_folder):
        ds = ImageFolderDataset(str(image_folder), image_size=8)
        labels = load_labels(ds)
        assert ["cat", "red", "0"] in labels
        labels2 = load_labels(str(image_folder))
        assert sorted(map(tuple, labels)) == sorted(map(tuple, labels2))

    def test_token_vocab(self):
        tok = Token([["red", "circle"], ["blue", "square", "small"]])
        assert tok.num_pairs == 6          # 5 words + pad
        assert tok.sequence_len == 3
        arr = tok.parse()
        assert arr.shape == (2, 3)
        assert arr[0, 2] == 0              # padded
        assert (tok.caption_mask() == (arr != 0)).all()
        assert tok.decode(arr[1]) == ["blue", "square", "small"]
        novel = tok.parse([["red", "square"]])
        assert novel.shape == (1, 3) and novel[0, 2] == 0

    def test_image_paths_taming_range(self, image_folder):
        paths = sorted(str(p) for p in image_folder.rglob("*.png"))
        ds = ImagePaths(paths, size=8, labels={"cls": list(range(len(paths)))})
        item = ds[0]
        assert item["image"].shape == (8, 8, 3)
        assert item["image"].min() >= -1.0 and item["image"].max() <= 1.0
        assert item["image"].min() < 0    # actually in [-1,1], not [0,1]
        assert item["cls"] == 0


def test_parallel_decode_preserves_order_and_skips_errors(tmp_path):
    _make_shards(tmp_path, n_shards=2, per_shard=8)
    serial = list(WebDataset(str(tmp_path), split_by_host=False)
                  .decode(image_size=8).map(lambda s: s["__key__"]))
    par = list(WebDataset(str(tmp_path), split_by_host=False)
               .decode(image_size=8, workers=4).map(lambda s: s["__key__"]))
    assert par == serial  # order-preserving

    # corrupt member: parallel path must skip it like the serial path
    path = tmp_path / "mix.tar"
    with tarfile.open(path, "w") as tf:
        for key, data in (("a", _png_bytes((1, 2, 3))), ("b", b"JUNK"),
                          ("c", _png_bytes((4, 5, 6)))):
            info = tarfile.TarInfo(f"{key}.png")
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
    out = [s["__key__"] for s in
           WebDataset(str(path), split_by_host=False).decode(workers=3)]
    assert out == ["a", "c"]
