"""Scanned multi-step (train_steps) ≡ k single-step dispatches for every
trainer family. The VAE/VQGAN paths precompute the single-step key and
temperature streams and scan them as inputs, so the equality is exact (f32),
not just statistical. DalleTrainer's equivalence test lives in
test_trainer_dalle.py."""

import jax
import numpy as np
import pytest

from dalle_tpu.config import (AnnealConfig, ClipConfig, DVAEConfig,
                              MeshConfig, OptimConfig, PrecisionConfig,
                              TrainConfig, VQGANConfig)


def _tc(tmp_path, name, batch=8, mesh=None):
    return TrainConfig(batch_size=batch, checkpoint_dir=str(tmp_path / name),
                       preflight_checkpoint=False,
                       mesh=mesh or MeshConfig(dp=8),
                       precision=PrecisionConfig(compute="float32"),
                       optim=OptimConfig(learning_rate=1e-3))


def _assert_same_params(p1, p2, rtol=1e-6, atol=1e-7):
    for a, b in zip(jax.tree.leaves(jax.device_get(p1)),
                    jax.tree.leaves(jax.device_get(p2))):
        assert np.isfinite(a).all()     # equal_nan must never mask a NaN run
        np.testing.assert_allclose(a, b, rtol=rtol, atol=atol)


def test_vae_train_steps_matches_singles(tmp_path):
    from dalle_tpu.train.trainer_vae import VAETrainer

    cfg = DVAEConfig(image_size=16, num_tokens=32, codebook_dim=16,
                     num_layers=2, num_resnet_blocks=0, hidden_dim=8)
    rng = np.random.RandomState(0)
    stack = rng.rand(3, 8, 16, 16, 3).astype(np.float32)

    tr1 = VAETrainer(cfg, _tc(tmp_path, "a"), AnnealConfig())
    singles = [tr1.train_step(stack[i])["loss"] for i in range(3)]

    tr2 = VAETrainer(cfg, _tc(tmp_path, "b"), AnnealConfig())
    m = tr2.train_steps(stack)
    assert tr2._host_step == 3
    np.testing.assert_allclose(m["loss"], singles[-1], rtol=1e-6)
    np.testing.assert_allclose(m["loss_mean"], np.mean(singles), rtol=1e-6)
    _assert_same_params(tr1.state.params, tr2.state.params)


@pytest.mark.slow
def test_vqgan_gan_train_steps_matches_singles(tmp_path):
    """Loss-level equivalence for the two-optimizer GAN scan (keys/temps are
    bit-identical to the single-step stream by construction). At 117s the
    single most expensive default-tier test (r5 durations) → slow tier; the
    non-GAN scanned parity (dalle/clip/vae) and the single-step GAN path
    keep default-tier coverage of both halves. Param-level
    comparison is deliberately NOT asserted: the VQ argmin sits on discrete
    decision boundaries where the f32 reassociation freedom of a different
    XLA schedule can flip a near-tie code assignment, changing gradients
    discontinuously — observed as run-to-run drift up to ~1e-4 on norm
    biases. The shared scan lifter (train_state.make_scanned_steps) is held
    to exact param equality by the VAE/CLIP/DALLE tests; this test guards
    the VQGAN-specific plumbing (xs ordering, temp/key streams, GAN state
    threading)."""
    from dalle_tpu.models.gan import GANLossConfig
    from dalle_tpu.train.trainer_vqgan import VQGANTrainer

    # 32x32: the 16x16/ch8 variant NaNs immediately (the disc's stride-2
    # stack degenerates) and equal_nan comparisons would vacuously pass
    cfg = VQGANConfig(embed_dim=16, n_embed=64, z_channels=16, resolution=32,
                      ch=16, ch_mult=(1, 2), num_res_blocks=1,
                      attn_resolutions=(16,))
    lc = GANLossConfig(disc_start=0, perceptual_weight=0.0)
    rng = np.random.RandomState(1)
    stack = (rng.rand(2, 8, 32, 32, 3).astype(np.float32)) * 2 - 1

    tr1 = VQGANTrainer(cfg, _tc(tmp_path, "a"), loss_cfg=lc)
    singles = [tr1.train_step(stack[i])["loss"] for i in range(2)]
    assert np.isfinite(singles).all()

    tr2 = VQGANTrainer(cfg, _tc(tmp_path, "b"), loss_cfg=lc)
    m = tr2.train_steps(stack)
    assert tr2._host_step == 2
    assert set(m) >= {"loss", "loss_mean", "disc_loss", "nll_loss",
                      "quant_loss", "g_loss", "d_weight"}
    np.testing.assert_allclose(m["loss"], singles[-1], rtol=1e-3)
    np.testing.assert_allclose(m["loss_mean"], np.mean(singles), rtol=1e-3)
    for leaf in jax.tree.leaves(jax.device_get(tr2.state.params)):
        assert np.isfinite(leaf).all()


def test_fit_with_scan_steps(tmp_path):
    """fit(scan_steps=2) stacks the batch stream through train_steps: same
    loss trajectory as the single-step fit (rng-free config), checkpoint and
    step bookkeeping intact, odd tail handled as k=1 stacks."""
    from dalle_tpu.config import DalleConfig
    from dalle_tpu.parallel.mesh import build_mesh
    from dalle_tpu.train.trainer_dalle import DalleTrainer

    cfg = DalleConfig(num_text_tokens=32, text_seq_len=8, dim=32, depth=2,
                      heads=2, dim_head=16, image_size=16,
                      image_vocab_size=32, image_fmap_size=4)
    rng = np.random.RandomState(3)
    batches = [(rng.randint(1, 32, (8, 8)), rng.randint(0, 32, (8, 16)))
               for _ in range(5)]            # odd count → tail group of 1

    mesh_cfg = MeshConfig(dp=8)
    base = dict(batch_size=8, preflight_checkpoint=False, mesh=mesh_cfg,
                precision=PrecisionConfig(compute="float32"),
                optim=OptimConfig(learning_rate=1e-2), save_every_steps=4,
                metrics_every=1)
    tr1 = DalleTrainer(
        cfg, TrainConfig(checkpoint_dir=str(tmp_path / "a"), **base),
        mesh=build_mesh(mesh_cfg))
    for b in batches:
        tr1.train_step(*b)

    tr2 = DalleTrainer(
        cfg, TrainConfig(checkpoint_dir=str(tmp_path / "b"), scan_steps=2,
                         **base),
        mesh=build_mesh(mesh_cfg))
    tr2.fit(iter(batches))
    assert tr2._host_step == 5
    for a, b in zip(jax.tree.leaves(jax.device_get(tr1.state.params)),
                    jax.tree.leaves(jax.device_get(tr2.state.params))):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6)


def test_dalle_train_steps_matches_singles_with_rng(tmp_path):
    """The advisor-flagged gap: the DALLE scanned path must be bit-identical
    to k single dispatches in rng modes too (null_cond_prob > 0 + dropout),
    not just the rng-free config — the per-step keys are precomputed on the
    host exactly as train_step computes them and scanned as inputs."""
    from dalle_tpu.config import DalleConfig
    from dalle_tpu.train.trainer_dalle import DalleTrainer

    cfg = DalleConfig(num_text_tokens=32, text_seq_len=8, dim=32, depth=2,
                      heads=2, dim_head=16, image_size=16,
                      image_vocab_size=32, image_fmap_size=4,
                      attn_dropout=0.1, ff_dropout=0.1)
    rng = np.random.RandomState(4)
    texts = rng.randint(1, 32, (3, 8, 8))
    ids = rng.randint(0, 32, (3, 8, 16))

    tr1 = DalleTrainer(cfg, _tc(tmp_path, "a"), null_cond_prob=0.2)
    singles = [tr1.train_step(texts[i], ids[i])["loss"] for i in range(3)]

    tr2 = DalleTrainer(cfg, _tc(tmp_path, "b"), null_cond_prob=0.2)
    m = tr2.train_steps(texts, ids)
    assert tr2._host_step == 3
    np.testing.assert_allclose(m["loss"], singles[-1], rtol=1e-6)
    np.testing.assert_allclose(m["loss_mean"], np.mean(singles), rtol=1e-6)
    _assert_same_params(tr1.state.params, tr2.state.params)


def test_stack_batches_ragged_group_falls_back_to_singles():
    """A short batch mid-stream (drop_last=False loaders, webdataset
    batched(partial=True)) must not crash np.stack — the ragged group drains
    as singles and stacking resumes on the next homogeneous group."""
    from dalle_tpu.train.base_trainer import BaseTrainer

    full = lambda: (np.zeros((8, 4)), np.zeros((8, 2)))
    short = lambda: (np.zeros((5, 4)), np.zeros((5, 2)))
    stream = [full(), short(), full(), full(), full()]
    out = list(BaseTrainer._stack_batches(None, iter(stream), 2))
    # group 1 (full, short) is ragged → 2 singles; group 2 stacks; tail single
    assert [s for s, _ in out] == [False, False, True, False]
    assert out[2][1][0].shape == (2, 8, 4)


@pytest.mark.slow  # ~8s; the scan≡singles invariant stays fast-tier on the
# vae and dalle(+rng) trainers — clip joins the vqgan variant in the slow tier
def test_clip_train_steps_matches_singles(tmp_path):
    from dalle_tpu.train.trainer_clip import CLIPTrainer

    cfg = ClipConfig(dim_text=32, dim_image=32, dim_latent=32,
                     num_text_tokens=64, text_enc_depth=1, text_seq_len=8,
                     text_heads=2, visual_enc_depth=1, visual_heads=2,
                     visual_image_size=16, visual_patch_size=8)
    rng = np.random.RandomState(2)
    texts = rng.randint(1, 64, (3, 8, 8))
    imgs = rng.rand(3, 8, 16, 16, 3).astype(np.float32)

    tr1 = CLIPTrainer(cfg, _tc(tmp_path, "a"))
    singles = [tr1.train_step(texts[i], imgs[i])["loss"] for i in range(3)]

    tr2 = CLIPTrainer(cfg, _tc(tmp_path, "b"))
    m = tr2.train_steps(texts, imgs)
    assert tr2._host_step == 3
    np.testing.assert_allclose(m["loss"], singles[-1], rtol=1e-6)
    np.testing.assert_allclose(m["loss_mean"], np.mean(singles), rtol=1e-6)
    _assert_same_params(tr1.state.params, tr2.state.params)
