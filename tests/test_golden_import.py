"""Weights-level golden tests for the pretrained-checkpoint importers.

The reference's pretrained wrappers exist to be bit-compatible with published
torch checkpoints (dalle_pytorch/vae.py:103-130 OpenAI pkls; :154-217 taming
ckpt+yaml). With zero egress the real files aren't fetchable, so these tests
build tiny torch-layout state dicts with random weights and verify that the
converted flax models reproduce an *independent torch oracle* of each
architecture: same codebook indices, same reconstructions. That validates both
the key/transpose mapping and the native flax architectures numerically.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn.functional as F  # noqa: E402

from dalle_tpu.config import VQGANConfig  # noqa: E402
from dalle_tpu.models.pretrained import (OpenAIDecoder, OpenAIEncoder,  # noqa: E402
                                         _convert_openai_state,
                                         convert_vqgan_state)
from dalle_tpu.models.vqgan import VQModel, init_vqgan  # noqa: E402

RNG = np.random.RandomState


def _conv(state, h, prefix, pad, stride=1):
    w = torch.as_tensor(state[f"{prefix}.w" if f"{prefix}.w" in state
                              else f"{prefix}.weight"])
    bkey = f"{prefix}.b" if f"{prefix}.b" in state else f"{prefix}.bias"
    b = torch.as_tensor(state[bkey]) if bkey in state else None
    return F.conv2d(h, w, b, padding=pad, stride=stride)


# ---------------------------------------------------------------------------
# OpenAI discrete VAE (reference vae.py:103-130; arch: openai/DALL-E enc/dec)
# ---------------------------------------------------------------------------

def _openai_block_state(rng, state, prefix, n_in, n_out):
    n_hid = n_out // 4
    shapes = [("conv_1", (n_hid, n_in, 3, 3)), ("conv_2", (n_hid, n_hid, 3, 3)),
              ("conv_3", (n_hid, n_hid, 3, 3)), ("conv_4", (n_out, n_hid, 1, 1))]
    for name, shp in shapes:
        state[f"{prefix}.res_path.{name}.w"] = rng.randn(*shp).astype(np.float32) * 0.2
        state[f"{prefix}.res_path.{name}.b"] = rng.randn(shp[0]).astype(np.float32) * 0.1
    if n_in != n_out:
        state[f"{prefix}.id_path.w"] = rng.randn(n_out, n_in, 1, 1).astype(np.float32) * 0.2
        state[f"{prefix}.id_path.b"] = rng.randn(n_out).astype(np.float32) * 0.1


def _openai_block_oracle(state, h, prefix):
    t = h
    for name, pad in (("conv_1", 1), ("conv_2", 1), ("conv_3", 1), ("conv_4", 0)):
        t = _conv(state, F.relu(t), f"{prefix}.res_path.{name}", pad)
    if f"{prefix}.id_path.w" in state:
        h = _conv(state, h, f"{prefix}.id_path", 0)
    return h + t


def make_openai_encoder_state(rng, n_hid=8, vocab=32):
    state = {"blocks.input.w": rng.randn(n_hid, 3, 7, 7).astype(np.float32) * 0.1,
             "blocks.input.b": rng.randn(n_hid).astype(np.float32) * 0.1}
    mults = (1, 1, 2, 4, 8)
    n_in = n_hid
    for g in range(1, 5):
        n_out = n_hid * mults[g]
        _openai_block_state(rng, state, f"blocks.group_{g}.block_1", n_in, n_out)
        n_in = n_out
    state["blocks.output.conv.w"] = rng.randn(vocab, n_in, 1, 1).astype(np.float32) * 0.1
    state["blocks.output.conv.b"] = rng.randn(vocab).astype(np.float32) * 0.1
    return state


def openai_encoder_oracle(state, x_nchw):
    h = _conv(state, x_nchw, "blocks.input", 3)
    for g in range(1, 5):
        h = _openai_block_oracle(state, h, f"blocks.group_{g}.block_1")
        if g < 4:
            h = F.max_pool2d(h, 2)
    return _conv(state, F.relu(h), "blocks.output.conv", 0)


def make_openai_decoder_state(rng, n_hid=8, n_init=16, vocab=32):
    state = {"blocks.input.w": rng.randn(n_init, vocab, 1, 1).astype(np.float32) * 0.1,
             "blocks.input.b": rng.randn(n_init).astype(np.float32) * 0.1}
    mults = (0, 8, 4, 2, 1)
    n_in = n_init
    for g in range(1, 5):
        n_out = n_hid * mults[g]
        _openai_block_state(rng, state, f"blocks.group_{g}.block_1", n_in, n_out)
        n_in = n_out
    state["blocks.output.conv.w"] = rng.randn(6, n_in, 1, 1).astype(np.float32) * 0.1
    state["blocks.output.conv.b"] = rng.randn(6).astype(np.float32) * 0.1
    return state


def openai_decoder_oracle(state, z_nchw):
    h = _conv(state, z_nchw, "blocks.input", 0)
    for g in range(1, 5):
        h = _openai_block_oracle(state, h, f"blocks.group_{g}.block_1")
        if g < 4:
            h = F.interpolate(h, scale_factor=2, mode="nearest")
    return _conv(state, F.relu(h), "blocks.output.conv", 0)


class TestOpenAIGolden:
    def test_encoder_matches_torch_oracle(self, rng):
        state = make_openai_encoder_state(rng)
        enc = OpenAIEncoder(n_hid=8, n_blk_per_group=1, vocab_size=32)
        x = rng.rand(2, 32, 32, 3).astype(np.float32)
        params = enc.init(jax.random.PRNGKey(0), jnp.asarray(x))
        params = _convert_openai_state(state, params)
        ours = np.asarray(enc.apply(params, jnp.asarray(x)))

        want = openai_encoder_oracle(
            state, torch.as_tensor(x.transpose(0, 3, 1, 2)))
        want = want.numpy().transpose(0, 2, 3, 1)
        np.testing.assert_allclose(ours, want, atol=2e-4, rtol=1e-4)
        # the property the wrapper exposes: identical codebook indices
        assert (ours.argmax(-1) == want.argmax(-1)).all()

    def test_decoder_matches_torch_oracle(self, rng):
        state = make_openai_decoder_state(rng)
        dec = OpenAIDecoder(n_hid=8, n_init=16, n_blk_per_group=1)
        ids = rng.randint(0, 32, (2, 4, 4))
        z = np.asarray(jax.nn.one_hot(ids, 32), np.float32)
        params = dec.init(jax.random.PRNGKey(0), jnp.asarray(z))
        params = _convert_openai_state(state, params)
        ours = np.asarray(dec.apply(params, jnp.asarray(z)))

        want = openai_decoder_oracle(
            state, torch.as_tensor(z.transpose(0, 3, 1, 2)))
        want = want.numpy().transpose(0, 2, 3, 1)
        np.testing.assert_allclose(ours, want, atol=2e-4, rtol=1e-4)


class TestDallEUnpickleShim:
    """The genuine CDN artifacts are FULL pickled ``dall_e`` modules — the
    reference needs the upstream package importable to unpickle them
    (vae.py:103-113). ``install_dall_e_stubs`` removes that dependency
    (VERDICT r2 #8): synthesize a full-module pickle referencing dall_e.*
    classes, drop the modules, and reload through freshly-installed stubs."""

    @staticmethod
    def _module_tree(state):
        import sys
        import torch.nn as tnn
        from dalle_tpu.models.pretrained import install_dall_e_stubs
        install_dall_e_stubs()
        enc_mod = sys.modules["dall_e.encoder"]
        conv_cls = sys.modules["dall_e.utils"].Conv2d
        root = enc_mod.Encoder()
        for key, val in state.items():
            *path, leaf, pname = key.split(".")
            node = root
            for p in path:
                if p not in node._modules:
                    node.add_module(p, enc_mod.EncoderBlock())
                node = node._modules[p]
            if leaf not in node._modules:
                node.add_module(leaf, conv_cls())
            node._modules[leaf].register_parameter(
                pname, tnn.Parameter(torch.as_tensor(val)))
        return root

    def test_full_module_pickle_roundtrip(self, rng, tmp_path):
        import sys
        from dalle_tpu.models.pretrained import install_dall_e_stubs
        state = make_openai_encoder_state(rng)
        root = self._module_tree(state)
        path = tmp_path / "encoder.pkl"
        torch.save(root, path)
        # simulate a process without the upstream package: the pickled class
        # references must resolve through freshly-created stubs
        for m in list(sys.modules):
            if m == "dall_e" or m.startswith("dall_e."):
                del sys.modules[m]
        install_dall_e_stubs()
        loaded = torch.load(path, map_location="cpu", weights_only=False)
        sd = loaded.state_dict()
        assert set(sd) == set(state)
        for k in state:
            np.testing.assert_array_equal(np.asarray(sd[k]), state[k])
        # and the recovered state feeds the tensor converter exactly as a
        # plain state_dict would (the from_pretrained path)
        enc = OpenAIEncoder(n_hid=8, n_blk_per_group=1, vocab_size=32)
        x = rng.rand(1, 16, 16, 3).astype(np.float32)
        params = enc.init(jax.random.PRNGKey(0), jnp.asarray(x))
        a = enc.apply(_convert_openai_state(state, params), jnp.asarray(x))
        b = enc.apply(_convert_openai_state(sd, params), jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


# ---------------------------------------------------------------------------
# taming VQGAN (reference vae.py:154-217 + taming module layout)
# ---------------------------------------------------------------------------

TINY = dict(resolution=16, ch=8, ch_mult=(1, 2), num_res_blocks=1,
            attn_resolutions=(8,), z_channels=4, embed_dim=4, n_embed=16,
            in_channels=3, out_ch=3, double_z=False)


def _gn_groups(c):
    return 32 if c % 32 == 0 else math.gcd(32, c)


def _add_conv(rng, state, prefix, cout, cin, k):
    state[f"{prefix}.weight"] = rng.randn(cout, cin, k, k).astype(np.float32) * 0.2
    state[f"{prefix}.bias"] = rng.randn(cout).astype(np.float32) * 0.1


def _add_norm(rng, state, prefix, c):
    state[f"{prefix}.weight"] = (1 + 0.1 * rng.randn(c)).astype(np.float32)
    state[f"{prefix}.bias"] = rng.randn(c).astype(np.float32) * 0.1


def _add_resblock(rng, state, prefix, cin, cout):
    _add_norm(rng, state, f"{prefix}.norm1", cin)
    _add_conv(rng, state, f"{prefix}.conv1", cout, cin, 3)
    _add_norm(rng, state, f"{prefix}.norm2", cout)
    _add_conv(rng, state, f"{prefix}.conv2", cout, cout, 3)
    if cin != cout:
        _add_conv(rng, state, f"{prefix}.nin_shortcut", cout, cin, 1)


def _add_attn(rng, state, prefix, c):
    _add_norm(rng, state, f"{prefix}.norm", c)
    for n in ("q", "k", "v", "proj_out"):
        _add_conv(rng, state, f"{prefix}.{n}", c, c, 1)


def make_vqgan_state(rng, cfg: VQGANConfig, gumbel=False):
    c = cfg
    state = {}
    # encoder
    _add_conv(rng, state, "encoder.conv_in", c.ch, c.in_channels, 3)
    cin, res = c.ch, c.resolution
    for lvl, mult in enumerate(c.ch_mult):
        cout = c.ch * mult
        for blk in range(c.num_res_blocks):
            _add_resblock(rng, state, f"encoder.down.{lvl}.block.{blk}", cin, cout)
            cin = cout
            if res in c.attn_resolutions:
                _add_attn(rng, state, f"encoder.down.{lvl}.attn.{blk}", cout)
        if lvl != len(c.ch_mult) - 1:
            _add_conv(rng, state, f"encoder.down.{lvl}.downsample.conv",
                      cout, cout, 3)
            res //= 2
    for blk in ("block_1", "block_2"):
        _add_resblock(rng, state, f"encoder.mid.{blk}", cin, cin)
    _add_attn(rng, state, "encoder.mid.attn_1", cin)
    _add_norm(rng, state, "encoder.norm_out", cin)
    _add_conv(rng, state, "encoder.conv_out", c.z_channels, cin, 3)

    # quantizer
    _add_conv(rng, state, "quant_conv", c.embed_dim, c.z_channels, 1)
    if gumbel:
        state["quantize.embed.weight"] = rng.randn(
            c.n_embed, c.embed_dim).astype(np.float32)
        _add_conv(rng, state, "quantize.proj", c.n_embed, c.embed_dim, 1)
    else:
        state["quantize.embedding.weight"] = rng.randn(
            c.n_embed, c.embed_dim).astype(np.float32)
    _add_conv(rng, state, "post_quant_conv", c.z_channels, c.embed_dim, 1)

    # decoder
    num_levels = len(c.ch_mult)
    cin = c.ch * c.ch_mult[-1]
    res = c.resolution // 2 ** (num_levels - 1)
    _add_conv(rng, state, "decoder.conv_in", cin, c.z_channels, 3)
    for blk in ("block_1", "block_2"):
        _add_resblock(rng, state, f"decoder.mid.{blk}", cin, cin)
    _add_attn(rng, state, "decoder.mid.attn_1", cin)
    for lvl in reversed(range(num_levels)):
        cout = c.ch * c.ch_mult[lvl]
        for blk in range(c.num_res_blocks + 1):
            _add_resblock(rng, state, f"decoder.up.{lvl}.block.{blk}", cin, cout)
            cin = cout
            if res in c.attn_resolutions:
                _add_attn(rng, state, f"decoder.up.{lvl}.attn.{blk}", cout)
        if lvl != 0:
            _add_conv(rng, state, f"decoder.up.{lvl}.upsample.conv", cout, cout, 3)
            res *= 2
    _add_norm(rng, state, "decoder.norm_out", cin)
    _add_conv(rng, state, "decoder.conv_out", c.out_ch, cin, 3)
    return state


def _t_gn(state, h, prefix):
    c = h.shape[1]
    return F.group_norm(h, _gn_groups(c), torch.as_tensor(state[f"{prefix}.weight"]),
                        torch.as_tensor(state[f"{prefix}.bias"]), eps=1e-6)


def _t_swish(t):
    return t * torch.sigmoid(t)


def _t_resblock(state, h, prefix):
    t = _conv(state, _t_swish(_t_gn(state, h, f"{prefix}.norm1")), f"{prefix}.conv1", 1)
    t = _conv(state, _t_swish(_t_gn(state, t, f"{prefix}.norm2")), f"{prefix}.conv2", 1)
    if f"{prefix}.nin_shortcut.weight" in state:
        h = _conv(state, h, f"{prefix}.nin_shortcut", 0)
    return h + t


def _t_attn(state, h, prefix):
    b, c, hh, ww = h.shape
    hn = _t_gn(state, h, f"{prefix}.norm")
    q = _conv(state, hn, f"{prefix}.q", 0).reshape(b, c, hh * ww).permute(0, 2, 1)
    k = _conv(state, hn, f"{prefix}.k", 0).reshape(b, c, hh * ww)
    v = _conv(state, hn, f"{prefix}.v", 0).reshape(b, c, hh * ww)
    w = torch.softmax(torch.bmm(q, k) * c ** -0.5, dim=2)       # (b, i, j)
    out = torch.bmm(v, w.permute(0, 2, 1)).reshape(b, c, hh, ww)
    return h + _conv(state, out, f"{prefix}.proj_out", 0)


def vqgan_encoder_oracle(state, cfg: VQGANConfig, x_nchw):
    c = cfg
    h = _conv(state, x_nchw, "encoder.conv_in", 1)
    res = c.resolution
    for lvl in range(len(c.ch_mult)):
        for blk in range(c.num_res_blocks):
            h = _t_resblock(state, h, f"encoder.down.{lvl}.block.{blk}")
            if res in c.attn_resolutions:
                h = _t_attn(state, h, f"encoder.down.{lvl}.attn.{blk}")
        if lvl != len(c.ch_mult) - 1:
            h = _conv(state, F.pad(h, (0, 1, 0, 1)),
                      f"encoder.down.{lvl}.downsample.conv", 0, stride=2)
            res //= 2
    h = _t_resblock(state, h, "encoder.mid.block_1")
    h = _t_attn(state, h, "encoder.mid.attn_1")
    h = _t_resblock(state, h, "encoder.mid.block_2")
    h = _t_swish(_t_gn(state, h, "encoder.norm_out"))
    return _conv(state, h, "encoder.conv_out", 1)


def vqgan_decoder_oracle(state, cfg: VQGANConfig, z_nchw):
    c = cfg
    num_levels = len(c.ch_mult)
    res = c.resolution // 2 ** (num_levels - 1)
    h = _conv(state, z_nchw, "decoder.conv_in", 1)
    h = _t_resblock(state, h, "decoder.mid.block_1")
    h = _t_attn(state, h, "decoder.mid.attn_1")
    h = _t_resblock(state, h, "decoder.mid.block_2")
    for lvl in reversed(range(num_levels)):
        for blk in range(c.num_res_blocks + 1):
            h = _t_resblock(state, h, f"decoder.up.{lvl}.block.{blk}")
            if res in c.attn_resolutions:
                h = _t_attn(state, h, f"decoder.up.{lvl}.attn.{blk}")
        if lvl != 0:
            h = F.interpolate(h, scale_factor=2, mode="nearest")
            h = _conv(state, h, f"decoder.up.{lvl}.upsample.conv", 1)
            res *= 2
    h = _t_swish(_t_gn(state, h, "decoder.norm_out"))
    return _conv(state, h, "decoder.conv_out", 1)


class TestVQGANGolden:
    def test_vq_indices_match_torch_oracle(self, rng):
        cfg = VQGANConfig(**TINY)
        model, params = init_vqgan(cfg, jax.random.PRNGKey(0))
        state = make_vqgan_state(rng, cfg)
        params = convert_vqgan_state(state, params, cfg)
        img = (rng.rand(2, 16, 16, 3).astype(np.float32) * 2 - 1)

        ours = np.asarray(model.apply(params, jnp.asarray(img),
                                      method=VQModel.get_codebook_indices))

        z = vqgan_encoder_oracle(state, cfg,
                                 torch.as_tensor(img.transpose(0, 3, 1, 2)))
        z = _conv(state, z, "quant_conv", 0)
        flat = z.permute(0, 2, 3, 1).reshape(-1, cfg.embed_dim)
        book = torch.as_tensor(state["quantize.embedding.weight"])
        dist = (flat.pow(2).sum(1, keepdim=True)
                - 2 * flat @ book.T + book.pow(2).sum(1)[None, :])
        want = dist.argmin(1).reshape(2, -1).numpy()
        assert (ours == want).all()

    def test_vq_decode_code_matches_torch_oracle(self, rng):
        cfg = VQGANConfig(**TINY)
        model, params = init_vqgan(cfg, jax.random.PRNGKey(0))
        state = make_vqgan_state(rng, cfg)
        params = convert_vqgan_state(state, params, cfg)
        ids = rng.randint(0, cfg.n_embed, (2, 64))

        ours = np.asarray(model.apply(params, jnp.asarray(ids),
                                      method=VQModel.decode_code))

        book = torch.as_tensor(state["quantize.embedding.weight"])
        quant = book[torch.as_tensor(ids)].reshape(2, 8, 8, cfg.embed_dim)
        quant = quant.permute(0, 3, 1, 2)
        z = _conv(state, quant, "post_quant_conv", 0)
        want = vqgan_decoder_oracle(state, cfg, z).numpy().transpose(0, 2, 3, 1)
        np.testing.assert_allclose(ours, want, atol=5e-4, rtol=1e-4)

    def test_gumbel_indices_and_decode_match_oracle(self, rng):
        cfg = VQGANConfig(**dict(TINY, quantizer="gumbel"))
        model, params = init_vqgan(cfg, jax.random.PRNGKey(0))
        state = make_vqgan_state(rng, cfg, gumbel=True)
        params = convert_vqgan_state(state, params, cfg)
        img = (rng.rand(2, 16, 16, 3).astype(np.float32) * 2 - 1)

        ours = np.asarray(model.apply(params, jnp.asarray(img),
                                      method=VQModel.get_codebook_indices))
        z = vqgan_encoder_oracle(state, cfg,
                                 torch.as_tensor(img.transpose(0, 3, 1, 2)))
        z = _conv(state, z, "quant_conv", 0)
        logits = _conv(state, z, "quantize.proj", 0)
        want = logits.argmax(1).reshape(2, -1).numpy()
        assert (ours == want).all()

        # decode path shares the converted codebook (quantize.embed.weight)
        ids = rng.randint(0, cfg.n_embed, (2, 64))
        ours_rec = np.asarray(model.apply(params, jnp.asarray(ids),
                                          method=VQModel.decode_code))
        book = torch.as_tensor(state["quantize.embed.weight"])
        quant = book[torch.as_tensor(ids)].reshape(2, 8, 8, cfg.embed_dim)
        zq = _conv(state, quant.permute(0, 3, 1, 2), "post_quant_conv", 0)
        want_rec = vqgan_decoder_oracle(state, cfg, zq).numpy().transpose(0, 2, 3, 1)
        np.testing.assert_allclose(ours_rec, want_rec, atol=5e-4, rtol=1e-4)


# ---------------------------------------------------------------------------
# LPIPS vgg weights import (reference taming/util.py:5-44 + lpips.py:11-54)
# ---------------------------------------------------------------------------

class TestLPIPSImport:
    def test_vgg_and_lin_import_match_torch_oracle(self, rng):
        from dalle_tpu.models.lpips import (_SCALE, _SHIFT, _VGG_SLICES,
                                            init_lpips, load_torch_weights)
        # narrow VGG-16-shaped state dict (full widths are slow on CPU);
        # the importer only keys on torchvision's features.{idx} layout
        widths = {0: 8, 2: 8, 5: 12, 7: 12, 10: 16, 12: 16, 14: 16,
                  17: 24, 19: 24, 21: 24, 24: 24, 26: 24, 28: 24}
        import dalle_tpu.models.lpips as lpips_mod
        slices_narrow = ((8, 8), (12, 12), (16, 16, 16), (24, 24, 24),
                        (24, 24, 24))
        orig = lpips_mod._VGG_SLICES
        lpips_mod._VGG_SLICES = slices_narrow
        try:
            vgg_state, lin_state = {}, {}
            cin = 3
            for idx, cout in widths.items():
                vgg_state[f"features.{idx}.weight"] = (
                    rng.randn(cout, cin, 3, 3).astype(np.float32) * 0.2)
                vgg_state[f"features.{idx}.bias"] = (
                    rng.randn(cout).astype(np.float32) * 0.1)
                cin = cout
            for i, ch in enumerate((8, 12, 16, 24, 24)):
                lin_state[f"lin{i}.model.1.weight"] = np.abs(
                    rng.randn(1, ch, 1, 1)).astype(np.float32)

            model, params = init_lpips(jax.random.PRNGKey(0), image_size=16)
            params = load_torch_weights(params, vgg_state, lin_state)
            x = (rng.rand(2, 16, 16, 3).astype(np.float32) * 2 - 1)
            y = (rng.rand(2, 16, 16, 3).astype(np.float32) * 2 - 1)
            ours = np.asarray(model.apply(params, jnp.asarray(x), jnp.asarray(y)))

            def feats(t):
                outs, h, it = [], t, iter(sorted(widths))
                for s, chans in enumerate(slices_narrow):
                    if s > 0:
                        h = F.max_pool2d(h, 2)
                    for _ in chans:
                        h = F.relu(_conv(vgg_state, h, f"features.{next(it)}", 1))
                    outs.append(h)
                return outs

            shift = torch.as_tensor(_SHIFT).reshape(1, 3, 1, 1)
            scale = torch.as_tensor(_SCALE).reshape(1, 3, 1, 1)
            tx = (torch.as_tensor(x.transpose(0, 3, 1, 2)) - shift) / scale
            ty = (torch.as_tensor(y.transpose(0, 3, 1, 2)) - shift) / scale
            want = 0.0
            for i, (a, b) in enumerate(zip(feats(tx), feats(ty))):
                na = a / (a.pow(2).sum(1, keepdim=True).sqrt() + 1e-10)
                nb = b / (b.pow(2).sum(1, keepdim=True).sqrt() + 1e-10)
                d = (na - nb) ** 2
                w = torch.as_tensor(lin_state[f"lin{i}.model.1.weight"])
                want = want + F.conv2d(d, w).mean(dim=(1, 2, 3))
            np.testing.assert_allclose(ours, want.numpy(), atol=1e-4, rtol=1e-4)
        finally:
            lpips_mod._VGG_SLICES = orig
