"""Continuous-batching serve engine (dalle_tpu/serve): scheduling
invariants (work-conserving slots, FIFO fairness, drain semantics) and the
correctness bar speculative decode set — per-request outputs TOKEN-EXACT
against single-request ``generate_images_tokens`` under the same per-request
key, for any admission order."""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dalle_tpu.config import DalleConfig
from dalle_tpu.models.dalle import DALLE, init_dalle
from dalle_tpu.serve import DecodeEngine, RequestQueue, SlotScheduler

# ceiling = the module's cold full-run total (re-measured 745 with the
# graftloom shared-prefix + chunked-prefill matrix; was 722 pre-graftloom)
# + ~15% slack for cross-jax-version compile-count variance (the
# test_speculative convention). Since PR 7 engines over the same model
# object share compiled programs per config key (serve/engine.py
# _shared_programs), so same-config tests stopped paying repeat compiles;
# an engine change that recompiles per admission, per slot count or per
# engine INSTANCE would blow straight through this.
pytestmark = pytest.mark.recompile_budget(860)

CFG = dict(num_text_tokens=32, text_seq_len=6, dim=32, depth=2, heads=2,
           dim_head=16, image_size=16, image_vocab_size=24, image_fmap_size=4)

TEXTS = [np.array([3, 4, 5, 0, 0, 0], np.int32),
         np.array([7, 8, 0, 0, 0, 0], np.int32),
         np.array([9, 1, 2, 3, 0, 0], np.int32),
         np.array([5, 5, 0, 0, 0, 0], np.int32),
         np.array([1, 2, 3, 4, 5, 6], np.int32)]


@pytest.fixture(scope="module")
def model_params():
    cfg = DalleConfig(**CFG)
    return init_dalle(cfg, jax.random.PRNGKey(0), batch=2)


@pytest.fixture(scope="module")
def refs100(model_params):
    """Sequential single-request references, seed 100+i per TEXTS[i] —
    shared by every f32 default-mode exactness test (eager references are
    the expensive half of these tests on the 1-core CI box)."""
    model, params = model_params
    return {i: _reference(model, params, t, 100 + i)
            for i, t in enumerate(TEXTS)}


def _reference(model, params, text, seed, **kw):
    ids = model.apply(params, jnp.asarray(text[None]),
                      jax.random.PRNGKey(seed),
                      method=DALLE.generate_images_tokens, **kw)
    return np.asarray(ids[0])


# ---------------------------------------------------------------------------
# host-side pieces (no jax)
# ---------------------------------------------------------------------------

def test_queue_fifo_close_drain():
    q = RequestQueue()
    r1 = q.submit(np.zeros(6, np.int32), seed=1)
    r2 = q.submit(np.zeros(6, np.int32), seed=2)
    assert q.qsize() == 2 and not q.drained
    taken = q.take(1)
    assert [r.request_id for r in taken] == [r1.request_id]
    q.close()
    with pytest.raises(RuntimeError):
        q.submit(np.zeros(6, np.int32), seed=3)
    assert not q.drained                      # r2 still queued
    assert q.take(5) == [r2]
    assert q.drained
    assert q.wait_nonempty(timeout=0.01) is False   # closed+empty: no block


def test_queue_rejects_stale_explicit_ids():
    """A duplicate explicit id would silently alias two requests' results
    everywhere completions are keyed by id — the queue rejects any id at or
    below the issued high-water mark instead of tracking ids forever."""
    q = RequestQueue()
    q.submit(np.zeros(6, np.int32), seed=1)            # auto id 0
    with pytest.raises(ValueError):
        q.submit(np.zeros(6, np.int32), seed=2, request_id=0)
    q.submit(np.zeros(6, np.int32), seed=3, request_id=7)
    with pytest.raises(ValueError):
        q.submit(np.zeros(6, np.int32), seed=4, request_id=5)
    nxt = q.submit(np.zeros(6, np.int32), seed=5)      # auto resumes past 7
    assert nxt.request_id == 8
    with pytest.raises(ValueError):
        q.submit(np.zeros(6, np.int32), seed=6, max_tokens=0)


def test_queue_bounded_rejects_on_full():
    """maxsize bounds the BACKLOG: submit on a full queue raises QueueFull
    (the gateway's 429) instead of growing without bound, FIFO order is
    untouched, and taking frees capacity."""
    from dalle_tpu.serve import QueueFull
    q = RequestQueue(maxsize=2)
    r1 = q.submit(np.zeros(6, np.int32), seed=1)
    q.submit(np.zeros(6, np.int32), seed=2)
    with pytest.raises(QueueFull):
        q.submit(np.zeros(6, np.int32), seed=3)
    assert q.qsize() == 2                      # rejected, not enqueued
    assert q.take(1) == [r1]                   # FIFO across the rejection
    r4 = q.submit(np.zeros(6, np.int32), seed=4)   # take freed capacity
    assert [r.request_id for r in q.take(5)][-1] == r4.request_id
    with pytest.raises(ValueError):
        RequestQueue(maxsize=0)


def test_policy_queue_fifo_default_matches_base():
    """A PolicyQueue without an explicit policy is bit-identical to the
    FIFO base: same take order, nothing shed — the pinned default."""
    from dalle_tpu.serve import PolicyQueue
    pq = PolicyQueue(maxsize=3)
    ids = [pq.submit(np.zeros(6, np.int32), seed=i,
                     priority=i, deadline_at=None).request_id
           for i in range(3)]
    assert [r.request_id for r in pq.take(2)] == ids[:2]
    assert [r.request_id for r in pq.take(2)] == ids[2:]
    assert pq.shed_total == 0


def test_policy_queue_priority_deadline_order_and_shed():
    """PriorityDeadlinePolicy: priority tiers first, then earliest
    deadline, then FIFO; an already-expired request is shed at take time
    and handed to on_shed, never to a slot."""
    from dalle_tpu.serve import PolicyQueue, PriorityDeadlinePolicy
    shed = []
    pq = PolicyQueue(policy=PriorityDeadlinePolicy(),
                     on_shed=shed.append)
    now = time.perf_counter()
    lo = pq.submit(np.zeros(6, np.int32), seed=1)               # prio 0
    hi_late = pq.submit(np.zeros(6, np.int32), seed=2, priority=5)
    hi_soon = pq.submit(np.zeros(6, np.int32), seed=3, priority=5,
                        deadline_at=now + 100)
    expired = pq.submit(np.zeros(6, np.int32), seed=4,
                        deadline_at=now - 0.1)
    got = pq.take(2)
    # same tier: the deadlined request precedes the open-ended one
    assert [r.request_id for r in got] == [hi_soon.request_id,
                                           hi_late.request_id]
    assert [r.request_id for r in shed] == [expired.request_id]
    assert pq.shed_total == 1
    assert [r.request_id for r in pq.take(5)] == [lo.request_id]


def test_scheduler_invariants():
    from dalle_tpu.serve.queue import Request
    s = SlotScheduler(3)
    reqs = [Request(request_id=i, text=np.zeros(4, np.int32), seed=i)
            for i in range(5)]
    pairs = s.admit(reqs[:2])
    assert [p[0] for p in pairs] == [0, 1] and s.occupancy == 2 / 3
    # FIFO pairing: next admission lands in the remaining slot, in order
    s.admit(reqs[2:3])
    assert s.occupancy == 1.0 and s.free_slots() == []
    with pytest.raises(ValueError):
        s.admit(reqs[3:5])                    # over-admission must raise
    done = s.complete(1)
    assert done.request_id == 1 and s.free_slots() == [1]
    with pytest.raises(ValueError):
        s.complete(1)                         # double-complete must raise
    s.admit(reqs[3:4])
    assert s.admission_order == [0, 1, 2, 3]  # strict submission order


# ---------------------------------------------------------------------------
# engine: token-exactness for ragged admission orders
# ---------------------------------------------------------------------------

def test_engine_token_exact_ragged_admission(model_params, refs100):
    """5 requests through 2 shared-cache slots: admissions interleave with
    mid-flight decode (3 refill waves), yet every request's tokens equal
    single-request generation under its own key — the refill window and
    per-row decode change nothing another row can observe."""
    model, params = model_params
    refs = refs100
    q = RequestQueue()
    for i, t in enumerate(TEXTS):
        q.submit(t, seed=100 + i, request_id=i)
    q.close()
    eng = DecodeEngine(model, params, slots=2)
    done = eng.run(q)
    assert sorted(c.request_id for c in done) == list(range(5))
    for c in done:
        np.testing.assert_array_equal(c.tokens, refs[c.request_id])
        assert c.admitted_at >= c.submitted_at
        assert c.first_token_at >= c.admitted_at
        assert c.completed_at >= c.first_token_at
    # work-conserving: while the queue held requests, both slots were busy —
    # and the bar is non-vacuous (backlogged iterations really were sampled)
    assert eng.stats.occupancy_while_queued == 1.0
    assert eng.stats.occupancy_n > 0
    assert eng.stats.refills == 3             # [0,1], [2], then [3,4]


def test_engine_on_complete_streams_without_accumulating(model_params,
                                                         refs100):
    """Long-lived serving memory contract: with ``on_complete`` every
    completion is delivered as its last token lands and run() accumulates
    nothing — results are identical to the drain-and-return mode."""
    model, params = model_params
    refs = refs100
    q = RequestQueue()
    for i, t in enumerate(TEXTS[:3]):
        q.submit(t, seed=100 + i, request_id=i)
    q.close()
    eng = DecodeEngine(model, params, slots=2)
    streamed = []
    returned = eng.run(q, on_complete=streamed.append)
    assert returned == []
    assert sorted(c.request_id for c in streamed) == [0, 1, 2]
    for c in streamed:
        np.testing.assert_array_equal(c.tokens, refs[c.request_id])


def test_engine_use_kernel_pin_plumbs_and_stays_exact(model_params):
    """use_kernel=False pins dense attends through every serve layer (and
    generate_images_tokens accepts the same pin for the reference side) —
    on the CPU mesh auto already resolves dense, so this checks the plumb
    and that the pinned engine keeps the exactness contract."""
    model, params = model_params
    refs = {i: _reference(model, params, t, 100 + i, use_kernel=False)
            for i, t in enumerate(TEXTS[:3])}
    q = RequestQueue()
    for i, t in enumerate(TEXTS[:3]):
        q.submit(t, seed=100 + i, request_id=i)
    q.close()
    eng = DecodeEngine(model, params, slots=2, use_kernel=False)
    for c in eng.run(q):
        np.testing.assert_array_equal(c.tokens, refs[c.request_id])


@pytest.mark.slow  # ~13s; int8w (the engine DEFAULT since graftnum) covers
# the int8-KV machinery fast-tier below — the standalone bf16+int8KV+approx
# top-k mode keeps its exactness check in the slow tier
def test_engine_int8_cache_exact(model_params):
    """bf16 params + int8 KV + approximate top-k — the pre-graftnum serving
    fast path — stays token-exact vs the same-mode sequential reference."""
    from dalle_tpu.train.train_state import cast_floating
    model, params = model_params
    bf16 = cast_floating(params, jnp.bfloat16)
    refs = {i: _reference(model, bf16, t, 7 + i, cache_dtype=jnp.int8,
                          topk_approx=True, temperature=0.5)
            for i, t in enumerate(TEXTS[:3])}
    q = RequestQueue()
    for i, t in enumerate(TEXTS[:3]):
        q.submit(t, seed=7 + i, request_id=i)
    q.close()
    eng = DecodeEngine(model, bf16, slots=2, cache_dtype=jnp.int8,
                       topk_approx=True, temperature=0.5)
    for c in eng.run(q):
        np.testing.assert_array_equal(c.tokens, refs[c.request_id])


def test_engine_int8w_default_exact_bulk_and_trickle(model_params):
    """int8 weights + int8 KV — the serve-engine DEFAULT since the
    precision-flow audit certified it: tokens stay bit-exact vs same-mode
    single-request generation through BOTH admission paths. slots=2 with a
    closed 4-deep queue forces bulk refill windows; slots=3 with ragged
    per-request lengths staggers completions through the per-row trickle
    scatter-prefill."""
    from dalle_tpu.ops.quantize_weights import quantize_params_int8
    model, params = model_params
    qv = quantize_params_int8(params)
    refs = {i: _reference(model, qv, t, 300 + i, cache_dtype=jnp.int8)
            for i, t in enumerate(TEXTS)}

    # bulk: every admission covers >= half the slots -> refill window
    q = RequestQueue()
    for i, t in enumerate(TEXTS[:4]):
        q.submit(t, seed=300 + i, request_id=i)
    q.close()
    eng = DecodeEngine(model, qv, slots=2, cache_dtype=jnp.int8)
    for c in eng.run(q):
        np.testing.assert_array_equal(c.tokens, refs[c.request_id])

    # trickle: ragged lengths free slots one at a time mid-flight
    lens = [16, 3, 9, 1, 12]
    q = RequestQueue()
    for i, t in enumerate(TEXTS):
        q.submit(t, seed=300 + i, request_id=i, max_tokens=lens[i])
    q.close()
    eng = DecodeEngine(model, qv, slots=3, cache_dtype=jnp.int8)
    done = eng.run(q)
    assert sorted(c.request_id for c in done) == list(range(5))
    for c in done:
        assert c.tokens.shape == (lens[c.request_id],)
        np.testing.assert_array_equal(c.tokens,
                                      refs[c.request_id][:lens[c.request_id]])


def test_wrapper_serve_engine_defaults_to_int8w(model_params):
    """DalleWithVae.serve_engine() with no precision argument builds the
    int8-weights + int8-KV engine from the wrapper's cached derived tree,
    and its requests match the wrapper-mode sequential reference exactly."""
    from dalle_tpu.models.wrapper import DalleWithVae
    model, params = model_params
    dv = DalleWithVae(model, params, None)   # vae unused on the token path
    eng = dv.serve_engine(slots=2)
    assert eng.cache_dtype == jnp.int8
    assert "quant" in eng.params             # per-channel scales present
    int8_leaves = [l for l in jax.tree_util.tree_leaves(eng.params["params"])
                   if hasattr(l, "dtype") and l.dtype == jnp.int8]
    assert int8_leaves
    # the derived tree is the wrapper's cached int8w mode — a second engine
    # must reuse it, not re-quantize
    assert dv.serve_engine(slots=2).params is eng.params

    refs = {i: _reference(model, eng.params, t, 500 + i,
                          cache_dtype=jnp.int8)
            for i, t in enumerate(TEXTS[:2])}
    q = RequestQueue()
    for i, t in enumerate(TEXTS[:2]):
        q.submit(t, seed=500 + i, request_id=i)
    q.close()
    for c in eng.run(q):
        np.testing.assert_array_equal(c.tokens, refs[c.request_id])


def test_engine_axial_posemb_exact():
    """rotary off → the per-row axial positional-embedding gather path."""
    cfg = DalleConfig(**{**CFG, "rotary_emb": False})
    model, params = init_dalle(cfg, jax.random.PRNGKey(0), batch=2)
    refs = {i: _reference(model, params, t, 40 + i)
            for i, t in enumerate(TEXTS[:3])}
    q = RequestQueue()
    for i, t in enumerate(TEXTS[:3]):
        q.submit(t, seed=40 + i, request_id=i)
    q.close()
    eng = DecodeEngine(model, params, slots=2)
    for c in eng.run(q):
        np.testing.assert_array_equal(c.tokens, refs[c.request_id])


def test_engine_streaming_submissions(model_params):
    """Producer submits from another thread while the engine runs: no
    drain-the-batch wait — late requests slot into freed rows, all complete
    exactly, in FIFO admission order."""
    model, params = model_params
    refs = {i: _reference(model, params, t, 60 + i)
            for i, t in enumerate(TEXTS)}
    q = RequestQueue()
    q.submit(TEXTS[0], seed=60, request_id=0)

    def producer():
        for i in range(1, 5):
            time.sleep(0.01)
            q.submit(TEXTS[i], seed=60 + i, request_id=i)
        q.close()

    t = threading.Thread(target=producer)
    t.start()
    eng = DecodeEngine(model, params, slots=2)
    done = eng.run(q)
    t.join()
    assert sorted(c.request_id for c in done) == list(range(5))
    for c in done:
        np.testing.assert_array_equal(c.tokens, refs[c.request_id])


# ---------------------------------------------------------------------------
# engine: gates, exhaustion, observability
# ---------------------------------------------------------------------------

def test_engine_ragged_lengths_trickle_admission(model_params):
    """Per-request max_tokens (ragged service demand) + slots=3 so
    staggered completions admit through the per-row scatter-prefill path
    AND the bulk refill window: each request's tokens equal the FIRST n of
    its full single-request generation, and short rows free their slot
    early (multi-step sync > 1 exercises the K-granular refill too)."""
    model, params = model_params
    full = {i: _reference(model, params, t, 80 + i)
            for i, t in enumerate(TEXTS)}
    lens = [16, 3, 9, 1, 12]
    q = RequestQueue()
    for i, t in enumerate(TEXTS):
        q.submit(t, seed=80 + i, request_id=i, max_tokens=lens[i])
    q.close()
    eng = DecodeEngine(model, params, slots=3, steps_per_sync=2)
    done = eng.run(q)
    assert sorted(c.request_id for c in done) == list(range(5))
    for c in done:
        assert c.tokens.shape == (lens[c.request_id],)
        np.testing.assert_array_equal(c.tokens,
                                      full[c.request_id][:lens[c.request_id]])


def test_engine_rejects_sparse_config():
    cfg = DalleConfig(**{**CFG, "attn_types": ("full", "axial_row")})
    model, params = init_dalle(cfg, jax.random.PRNGKey(0), batch=2)
    with pytest.raises(ValueError, match="full attention"):
        DecodeEngine(model, params, slots=2)


def test_engine_max_steps_cutoff(model_params):
    """max_steps bounds the loop (bench/smoke harness knob): the engine
    returns only fully completed requests, never a truncated token list."""
    model, params = model_params
    q = RequestQueue()
    for i, t in enumerate(TEXTS[:2]):
        q.submit(t, seed=i, request_id=i)
    q.close()
    eng = DecodeEngine(model, params, slots=2)
    done = eng.run(q, max_steps=5)
    assert done == [] and eng.stats.steps == 5
    # the cutoff is not a graceful drain: consumed-but-unfinished requests
    # are reported, never silently dropped
    assert sorted(eng.stats.aborted_in_flight) == [0, 1]


def test_engine_spans_and_gauges(model_params):
    """Tracing on: every completed request leaves a serve/request +
    serve/request_ttft span (request_id arg, sane durations) and the
    queue-depth / slot-occupancy gauges and token counters are live."""
    from dalle_tpu import obs
    model, params = model_params
    tracer = obs.configure()
    try:
        q = RequestQueue()
        for i, t in enumerate(TEXTS[:3]):
            q.submit(t, seed=20 + i, request_id=i)
        q.close()
        eng = DecodeEngine(model, params, slots=2)
        done = eng.run(q)
        spans = tracer.snapshot_spans()
        by_name = {}
        for name, rel, dur, tid, depth, args in spans:
            by_name.setdefault(name, []).append((dur, args))
        for want in ("serve/request", "serve/request_ttft",
                     "serve/request_queue_wait"):
            got = by_name.get(want, [])
            assert len(got) == 3, f"missing {want} spans: {by_name.keys()}"
            ids = sorted(a["request_id"] for _, a in got)
            assert ids == [0, 1, 2]
            assert all(d >= 0 for d, _ in got)
        # queue wait ≤ TTFT per request: the wait span measures exactly the
        # submission→admission segment of the TTFT span
        qw = {a["request_id"]: d
              for d, a in by_name["serve/request_queue_wait"]}
        tt = {a["request_id"]: d for d, a in by_name["serve/request_ttft"]}
        assert all(qw[i] <= tt[i] for i in qw)
        m = obs.metrics_snapshot()
        assert m["serve.requests_completed_total"] == 3
        assert m["serve.tokens_emitted_total"] == sum(
            c.tokens.shape[0] for c in done)
        assert m["serve.slot_occupancy"] >= 0
        assert m["serve.queue_depth"] == 0
        assert m["serve.queue_wait_s"] >= 0
    finally:
        obs.disable()


# ---------------------------------------------------------------------------
# shared-prefix candidate groups + chunked prefill (graftloom)
# ---------------------------------------------------------------------------

def _submit_group(q, text, base_seed, n, *, gid, start_id, max_tokens=None):
    """The /v1/images fan-out shape: candidate i samples under
    base_seed + i, all members carry one group_id and identical text."""
    for i in range(n):
        q.submit(text, seed=base_seed + i, request_id=start_id + i,
                 max_tokens=max_tokens, group_id=gid, group_size=n,
                 group_index=i)


@pytest.fixture(scope="module")
def int8w_params(model_params):
    """One int8-quantized tree shared by every int8w graftloom test (the
    eager quantize pass is not free on the 1-core CI box)."""
    from dalle_tpu.ops.quantize_weights import quantize_params_int8
    return quantize_params_int8(model_params[1])


@pytest.fixture(scope="module")
def group_refs(model_params):
    """Sequential single-request references for the f32 group tests:
    TEXTS[0] under seeds 700..702 — computed once, sliced per test."""
    model, params = model_params
    return [_reference(model, params, TEXTS[0], 700 + i) for i in range(3)]


def test_engine_shared_prefix_group_exact_and_split_demotes(model_params,
                                                            group_refs):
    """Shared-prefix admission holds the PR4 bar, both when a group fits
    one pass and when it splits. (a) Both candidates of ONE prompt
    admitted together pay a single shared b=1 prefill (1 refill total, 1
    prefill saved), yet each candidate's tokens are bitwise its
    INDEPENDENT single-request generation under its own seed. (b) A
    3-candidate group through the same 2 slots: the first pass admits two
    members (cohort, shared prefill), the straggler lands alone in a later
    pass and demotes to the single trickle path — sharing degrades to
    fewer saved prefills, never to different bits."""
    model, params = model_params
    refs = group_refs

    q = RequestQueue()
    _submit_group(q, TEXTS[0], 700, 2, gid=1, start_id=0)
    q.close()
    eng = DecodeEngine(model, params, slots=2)
    done = eng.run(q)
    assert sorted(c.request_id for c in done) == [0, 1]
    for c in done:
        np.testing.assert_array_equal(c.tokens, refs[c.request_id])
    assert eng.stats.shared_refills == 1
    assert eng.stats.shared_prefills_saved == 1
    assert eng.stats.refills == 1             # ONE admission dispatch total

    q = RequestQueue()
    _submit_group(q, TEXTS[0], 700, 3, gid=2, start_id=0)
    q.close()
    eng = DecodeEngine(model, params, slots=2)
    done = eng.run(q)
    assert sorted(c.request_id for c in done) == [0, 1, 2]
    for c in done:
        np.testing.assert_array_equal(c.tokens, refs[c.request_id])
    assert eng.stats.shared_refills == 1      # the pass-1 pair
    assert eng.stats.shared_prefills_saved == 1


def test_engine_shared_prefix_cohort_beside_trickle_single(model_params,
                                                           group_refs):
    """One admission pass holding a cohort AND a lone single: the cohort
    rides the shared prefill, the single rides the per-row trickle path,
    and a partial-grid group (max_tokens) gets the exact reference prefix.
    slots=3 + steps_per_sync=2 reuses the ragged-admission programs."""
    model, params = model_params
    sref = _reference(model, params, TEXTS[1], 720)
    q = RequestQueue()
    q.submit(TEXTS[1], seed=720, request_id=0, max_tokens=9)
    _submit_group(q, TEXTS[0], 700, 2, gid=2, start_id=1, max_tokens=6)
    q.close()
    eng = DecodeEngine(model, params, slots=3, steps_per_sync=2)
    done = {c.request_id: c for c in eng.run(q)}
    assert sorted(done) == [0, 1, 2]
    np.testing.assert_array_equal(done[0].tokens, sref[:9])
    for i in range(2):
        np.testing.assert_array_equal(done[1 + i].tokens,
                                      group_refs[i][:6])
    assert eng.stats.shared_refills == 1
    assert eng.stats.shared_prefills_saved == 1


def test_engine_group_mismatched_text_demoted_not_shared(model_params):
    """Members claiming one group_id but carrying DIFFERENT texts (a misuse
    the gateway never produces) must not be prefilled with the first
    member's prompt: they demote to singles and produce exactly what the
    same two UNGROUPED requests produce (both demote to the identical
    window-admission program, so the comparison is bitwise by
    construction — and shared_refills stays 0)."""
    model, params = model_params

    def run(gid):
        q = RequestQueue()
        q.submit(TEXTS[0], seed=740, request_id=0, group_id=gid,
                 group_size=2, group_index=0)
        q.submit(TEXTS[1], seed=741, request_id=1, group_id=gid,
                 group_size=2, group_index=1)
        q.close()
        eng = DecodeEngine(model, params, slots=2)
        return {c.request_id: c.tokens for c in eng.run(q)}, eng.stats

    grouped, gstats = run(9)
    plain, _ = run(None)
    assert gstats.shared_refills == 0
    for i in (0, 1):
        np.testing.assert_array_equal(grouped[i], plain[i])


def test_engine_shared_prefix_int8w_and_int8kv_exact(model_params,
                                                     int8w_params):
    """The shared prefill holds the PR4 bar in the quantized serving modes:
    int8 weights + int8 KV (the audited default) and bf16 + int8 KV with
    approximate top-k — candidate tokens bitwise the same-mode independent
    references. The prefix KV depends only on the text, so broadcasting
    quantized kv AND scale rows is exact by construction.

    (Why the bf16 mode is pinned at the STATE level instead of via token
    references: the bf16 fast path has a PRE-existing, graftloom-
    independent low-bit wobble — the b=1 JITTED prefill can differ from
    the EAGER sequential reference in last-place bf16 bits on the CPU
    backend, flipping a rare near-tie sample. The per-row trickle path
    shows the identical flip with no groups involved (e.g. a lone seed-760
    request on this text through slots=3), so a bf16 token-vs-reference
    check here would test that wobble, not sharing. The sharing claim —
    shared admission ≡ per-row admission, every cache/scale/logits/key
    bit, for BOTH jitted programs — is seed-independent and pinned in
    test_engine_shared_refill_state_bitwise_eq_row_path on the int8w
    default, whose activations are the same bf16.)"""
    model, params = model_params

    qv = int8w_params
    refs = {i: _reference(model, qv, TEXTS[2], 750 + i,
                          cache_dtype=jnp.int8) for i in range(2)}
    q = RequestQueue()
    _submit_group(q, TEXTS[2], 750, 2, gid=4, start_id=0)
    q.close()
    eng = DecodeEngine(model, qv, slots=2, cache_dtype=jnp.int8)
    for c in eng.run(q):
        np.testing.assert_array_equal(c.tokens, refs[c.request_id])
    assert eng.stats.shared_refills == 1

    # chunked prefill through the same quantized mode (reusing the refs):
    # every chunk writes the same int8 cache rows + scale planes the
    # one-shot window would, so tokens stay bit-exact — 7 positions in 2s
    # dispatch as 2,2,2,1
    q = RequestQueue()
    for i in range(2):
        q.submit(TEXTS[2], seed=750 + i, request_id=i)
    q.close()
    eng = DecodeEngine(model, qv, slots=2, cache_dtype=jnp.int8,
                       prefill_chunk=2)
    for c in eng.run(q):
        np.testing.assert_array_equal(c.tokens, refs[c.request_id])
    assert eng.stats.prefill_chunks == 4


def test_engine_shared_refill_state_bitwise_eq_row_path(model_params,
                                                        int8w_params):
    """The seed-independent sharing invariant, on the REAL jitted serving
    programs: ONE shared b=1 prefill broadcast into N sibling rows
    produces EXACTLY the engine state N per-row scatter-prefills produce —
    every KV byte, every int8 scale plane, the first-token logits and both
    RNG lanes. Decode is the same program either way, so a candidate
    stream cannot diverge from ungrouped admission no matter the seed.
    Checked in the int8w+int8kv serve DEFAULT — bf16 activations, so the
    fragile-tie mode's logits dtype merge is covered, with quantized kv
    AND scale planes to broadcast (and the engine config shares its
    compiled programs with the token test above)."""
    model, params = model_params
    eng = DecodeEngine(model, int8w_params, slots=2, cache_dtype=jnp.int8)
    text1 = jnp.asarray(eng._pad_text(TEXTS[2])[None])
    seeds = jnp.asarray(np.array([760, 761], np.int32))
    n_rows = jnp.asarray(np.full((2,), eng.n_steps, np.int32))
    mask = jnp.asarray(np.ones((2,), bool))
    st_sh = eng._refill_shared_fn(eng.params, eng._init_state(), text1,
                                  seeds, n_rows, mask)
    st_row = eng._init_state()
    for row, s in enumerate((760, 761)):
        st_row = eng._refill_row_fn(eng.params, st_row, text1,
                                    jnp.int32(s), jnp.int32(eng.n_steps),
                                    jnp.int32(row))
    for name in st_sh["cache"]:
        a, b = st_sh["cache"][name], st_row["cache"][name]
        np.testing.assert_array_equal(np.asarray(a.kv), np.asarray(b.kv))
        if a.scale is not None:
            np.testing.assert_array_equal(np.asarray(a.scale),
                                          np.asarray(b.scale))
    for k in ("logits", "cur_key", "orig_key", "t_idx", "n_row", "active"):
        np.testing.assert_array_equal(np.asarray(st_sh[k]),
                                      np.asarray(st_row[k]))


def test_engine_chunked_prefill_exact_and_interleaves(model_params):
    """prefill_chunk=3 splits the 7-position window prefill (<bos> + 6
    text) into 3+3+1 chunks: (a) chunked tokens are BITWISE the unchunked
    engine's for the same workload (the satellite's chunked ≡ unchunked
    claim; the unchunked engine is itself pinned ≡ sequential generation
    by the admission tests above); (b) the TTFT-isolation property — a
    chunked admission arriving beside a still-decoding row dispatches its
    chunks interleaved with that row's decode steps (the step counter
    strictly advances between chunks), so a fat admission can't stall a
    neighbor for its whole prompt length. (prefill_chunk=0 engines never
    build chunk jobs — their host loop and pinned programs are the
    pre-graftloom ones, which the serve_refill/serve_decode graftir
    goldens hold byte-identical.)"""
    from dalle_tpu import obs
    model, params = model_params

    # r0 decodes the full grid; r1 frees its slot after 2 tokens so the
    # queued r2 admits (chunked) while r0 still has ~14 steps to go
    def run(prefill_chunk):
        q = RequestQueue()
        q.submit(TEXTS[0], seed=770, request_id=0)
        q.submit(TEXTS[1], seed=771, request_id=1, max_tokens=2)
        q.submit(TEXTS[2], seed=772, request_id=2)
        q.close()
        eng = DecodeEngine(model, params, slots=2,
                           prefill_chunk=prefill_chunk)
        return {c.request_id: c for c in eng.run(q)}, eng

    plain, _ = run(0)
    tracer = obs.configure()
    try:
        done, eng = run(3)
        chunk_spans = [args for name, _r, _d, _t, _dep, args
                       in tracer.snapshot_spans()
                       if name == "serve/prefill_chunk"]
    finally:
        obs.disable()
    assert sorted(done) == [0, 1, 2]
    for i in range(3):
        np.testing.assert_array_equal(done[i].tokens, plain[i].tokens)
    assert done[1].tokens.shape == (2,)
    # two chunked admissions ([r0,r1] window, then [r2]) of 3 chunks each
    assert eng.stats.prefill_chunks == 6
    assert [s["start"] for s in chunk_spans] == [0, 3, 6, 0, 3, 6]
    assert [s["width"] for s in chunk_spans] == [3, 3, 1, 3, 3, 1]
    # isolation: r2's chunks (the last 3) dispatched with r0 mid-decode —
    # decode steps landed between every pair of consecutive chunks
    steps = [s["step"] for s in chunk_spans[3:]]
    assert steps[0] < steps[1] < steps[2]

    # TRICKLE regime (slots=3): a later single admission below the window
    # threshold (2*1 < 3) must ALSO chunk — it becomes a one-row-masked
    # window job, not an unbounded one-shot row prefill — and its tokens
    # stay bitwise the chunk-off engine's (whose trickle path is pinned ≡
    # sequential generation by the ragged-admission test above)
    def run3(prefill_chunk):
        q = RequestQueue()
        q.submit(TEXTS[0], seed=780, request_id=0)
        q.submit(TEXTS[1], seed=781, request_id=1, max_tokens=2)
        q.submit(TEXTS[2], seed=782, request_id=2, max_tokens=2)
        q.submit(TEXTS[3], seed=783, request_id=3)
        q.close()
        eng = DecodeEngine(model, params, slots=3,
                           prefill_chunk=prefill_chunk)
        return {c.request_id: c for c in eng.run(q)}, eng

    plain3, off_eng = run3(0)
    assert off_eng.stats.prefill_chunks == 0
    done3, on_eng = run3(3)
    assert sorted(done3) == [0, 1, 2, 3]
    for i in range(4):
        np.testing.assert_array_equal(done3[i].tokens, plain3[i].tokens)
    # [r0,r1,r2] window (3 chunks) + r3's one-row trickle job (3 chunks)
    assert on_eng.stats.prefill_chunks == 6


def test_engine_decode_health_exact_with_quality_telemetry(model_params):
    """graftpulse decode-quality taps (engine decode_health=True): tokens
    stay BIT-exact vs the untapped engine and the single-request reference
    (the taps read the logits, consume no rng), and each completed request's
    serve/request span carries entropy / topk_mass / repeat_ratio args while
    the aggregate dalle_health_decode_* gauges go live."""
    import math
    from dalle_tpu import obs
    model, params = model_params
    refs = {i: _reference(model, params, t, 40 + i)
            for i, t in enumerate(TEXTS[:3])}

    def run(decode_health):
        q = RequestQueue()
        for i, t in enumerate(TEXTS[:3]):
            q.submit(t, seed=40 + i, request_id=i)
        q.close()
        eng = DecodeEngine(model, params, slots=2,
                           decode_health=decode_health)
        return eng.run(q)

    plain = {c.request_id: c.tokens for c in run(False)}
    tracer = obs.configure()
    try:
        tapped = run(True)
        for c in tapped:
            np.testing.assert_array_equal(c.tokens, refs[c.request_id])
            np.testing.assert_array_equal(c.tokens, plain[c.request_id])
        qspans = [args for name, _r, _d, _t, _dep, args
                  in tracer.snapshot_spans() if name == "serve/request"]
        assert len(qspans) == 3
        for args in qspans:
            assert math.isfinite(args["entropy"]) and args["entropy"] >= 0
            assert 0.0 <= args["topk_mass"] <= 1.0 + 1e-6
            assert 0.0 <= args["repeat_ratio"] <= 1.0
            assert "trace_id" in args   # per-request values ride span args,
            # never metric labels (graftlint: unbounded-metric-label)
        m = obs.metrics_snapshot()
        for g in ("health.decode_entropy", "health.decode_topk_mass",
                  "health.decode_repeat_ratio"):
            assert g in m, g
        assert not any("{" in k and "trace_id" in k for k in m)
    finally:
        obs.disable()
