#!/usr/bin/env python
"""Summarize a grafttrace run: step-time histogram + top-k slowest spans.

Input is any grafttrace output — a run directory (picks up every ``.jsonl``
inside, e.g. ``<checkpoint_dir>/obs/`` or a ``--trace`` export dir), a
``spans.jsonl``, or a ``MetricsLogger`` metrics JSONL. Span rows yield the
per-name aggregate and slowest-spans tables; metrics rows yield the
step-time histogram, the input-bound/compute-bound verdict from the
data-starvation ratio, and HBM/recompile callouts. See docs/OBSERVABILITY.md
for reading the output.

Examples:
  python scripts/obs_report.py ./checkpoints/obs
  python scripts/obs_report.py ./metrics.jsonl --top 20
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("path", help="run directory or .jsonl file")
    ap.add_argument("--top", type=int, default=10,
                    help="rows in the top-k span tables")
    args = ap.parse_args(argv)

    from dalle_tpu.obs.report import summarize_run
    if not os.path.exists(args.path):
        print(f"error: {args.path} does not exist", file=sys.stderr)
        return 2
    print(summarize_run(args.path, topk=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
