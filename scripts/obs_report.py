#!/usr/bin/env python
"""Summarize a grafttrace run: step-time histogram + top-k slowest spans.

Input is any grafttrace output — a run directory (picks up every ``.jsonl``
inside, e.g. ``<checkpoint_dir>/obs/`` or a ``--trace`` export dir), a
``spans.jsonl``, or a ``MetricsLogger`` metrics JSONL. Span rows yield the
per-name aggregate and slowest-spans tables; metrics rows yield the
step-time histogram, the input-bound/compute-bound verdict from the
data-starvation ratio, HBM/recompile callouts, and — when the graftpulse
``health/*`` columns are present (``--health`` runs) — the MODEL-HEALTH
verdict line naming the breaching detector and layer group. Zero-sample
sections (no completed requests, no steps) print ``n/a``, never NaN. See
docs/OBSERVABILITY.md for reading the output.

``--request <id>`` switches to graftscope's per-request view: every span
tagged with that trace_id (or engine request_id), from every thread the
request crossed — gateway connection thread, engine worker, a post-failover
replica — reassembled into one wall-clock-ordered timeline
(queue-wait → prefill → per-row decode → SSE flush). The id is the
``X-Request-Id`` response header / the ``trace_id`` in SSE events.

With graftlens fleet telemetry the same view crosses PROCESSES: point it
at a merged-spans export (``TelemetryCollector.export_merged_jsonl``, the
fleet smoke drops one under ``telemetry_artifacts/``) and the timeline
spans gateway thread → remote replica → failover target, with a ``proc``
column and a clock-offset-bound note. Summary mode additionally renders
native-histogram quantiles (p50/p95 from the ``_bucket{le=}`` series, not
raw samples), the per-tenant USAGE table, and the TELEMETRY verdict (a
loud LOSSY warning when a span/event ring overflowed).

Examples:
  python scripts/obs_report.py ./checkpoints/obs
  python scripts/obs_report.py ./metrics.jsonl --top 20
  python scripts/obs_report.py gateway_artifacts --request 8f2a9c0d1e2f3a4b
  python scripts/obs_report.py fleet_artifacts/telemetry_artifacts \\
      --request 8f2a9c0d1e2f3a4b   # cross-process merged timeline
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("path", help="run directory or .jsonl file")
    ap.add_argument("--top", type=int, default=10,
                    help="rows in the top-k span tables")
    ap.add_argument("--request", type=str, default=None, metavar="ID",
                    help="reassemble one request's cross-thread timeline "
                         "(trace_id from X-Request-Id / SSE events, or an "
                         "engine request_id)")
    args = ap.parse_args(argv)

    from dalle_tpu.obs.report import (format_request_timeline, load_jsonl,
                                      summarize_run)
    if not os.path.exists(args.path):
        print(f"error: {args.path} does not exist", file=sys.stderr)
        return 2
    if args.request is not None:
        paths = [args.path]
        if os.path.isdir(args.path):
            paths = [os.path.join(args.path, n)
                     for n in sorted(os.listdir(args.path))
                     if n.endswith(".jsonl")]
        rows = []
        for p in paths:
            rows.extend(load_jsonl(p))
        text = format_request_timeline(rows, args.request)
        print(text)
        return 0 if not text.startswith("(no spans") else 1
    print(summarize_run(args.path, topk=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
