#!/usr/bin/env python
"""Synthetic labeled-shapes dataset generator CLI.

Reference: /root/reference/sampler.py (SampleMaker, cairo-rendered shapes saved
as labeled PNGs, :275-388). Same output contract: a folder of images whose
filenames encode the caption ("medium_red_circle_00042.png") plus sidecar .txt
captions so both the filename-label flow (fork dalle.py) and the
TextImageDataset text-file flow (dalle_pytorch/loader.py) work.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--outdir", required=True)
    ap.add_argument("--count", type=int, default=None,
                    help="number of samples (default: all combinations × variants)")
    ap.add_argument("--image_size", type=int, default=128)
    ap.add_argument("--variants", type=int, default=4,
                    help="rotated/dithered variants per (color,shape,scale) combo")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--trace", type=str, default=None, metavar="DIR",
                    help="grafttrace dataset generation (span per phase), "
                         "exported to DIR (docs/OBSERVABILITY.md)")
    args = ap.parse_args(argv)

    from dalle_tpu.data.synthetic import ShapesDataset
    from dalle_tpu.obs import trace as obs_trace
    if args.trace:
        obs_trace.configure()
    with obs_trace.span("sampler/build_dataset"):
        ds = ShapesDataset(image_size=args.image_size, variants=args.variants,
                           seed=args.seed)
    with obs_trace.span("sampler/save_folder", outdir=args.outdir):
        n = ds.save_folder(args.outdir, count=args.count)
    print(f"wrote {n} image/caption pairs to {args.outdir}")
    if args.trace:
        os.makedirs(args.trace, exist_ok=True)
        obs_trace.export_chrome_trace(os.path.join(args.trace, "trace.json"))
        obs_trace.export_spans_jsonl(os.path.join(args.trace, "spans.jsonl"))
        print(f"[trace] exported to {args.trace}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
