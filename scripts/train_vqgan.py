#!/usr/bin/env python
"""Train a VQGAN image tokenizer (taming-stack parity) on TPU or the CPU mesh.

Reference: the taming Lightning harness (taming/main.py) driving
``VQModel``/``GumbelVQ`` with ``VQLPIPSWithDiscriminator`` — here a plain CLI
over ``VQGANTrainer`` (two-optimizer adversarial training in one jitted step).
LR follows taming's accumulate×ngpu×bs×base_lr rule (main.py:530-541) unless
--absolute_lr is passed.

Example:
  python scripts/train_vqgan.py --image_folder /tmp/shapes --resolution 64 \
      --ch 32 --ch_mult 1,2 --n_embed 256 --epochs 1 --batch_size 8 \
      --disc_start 1000
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import (add_compile_cache_args, add_health_args,  # noqa: E402
                     add_resilience_args, install_resilience,
                     add_overlap_args, add_profiler_args,
                     enable_compile_cache, health_obs_kwargs,
                     install_health_recorder, install_sigusr2_profiler,
                     overlap_train_kwargs)


def build_parser():
    ap = argparse.ArgumentParser(description=__doc__)
    data = ap.add_argument_group("data")
    data.add_argument("--image_folder", type=str, default=None)
    data.add_argument("--synthetic", action="store_true")

    model = ap.add_argument_group("model")
    model.add_argument("--resolution", type=int, default=256)
    model.add_argument("--n_embed", type=int, default=1024)
    model.add_argument("--embed_dim", type=int, default=256)
    model.add_argument("--z_channels", type=int, default=256)
    model.add_argument("--ch", type=int, default=128)
    model.add_argument("--ch_mult", type=str, default="1,1,2,2,4")
    model.add_argument("--num_res_blocks", type=int, default=2)
    model.add_argument("--attn_resolutions", type=str, default="16")
    model.add_argument("--dropout", type=float, default=0.0)
    model.add_argument("--gumbel", action="store_true",
                       help="GumbelVQ variant (taming vqgan.py:261-303)")

    loss = ap.add_argument_group("loss")
    loss.add_argument("--disc_start", type=int, default=10000)
    loss.add_argument("--disc_weight", type=float, default=0.8)
    loss.add_argument("--disc_num_layers", type=int, default=3)
    loss.add_argument("--disc_ndf", type=int, default=64)
    loss.add_argument("--disc_loss", type=str, default="hinge",
                      choices=["hinge", "vanilla"])
    loss.add_argument("--codebook_weight", type=float, default=1.0)
    loss.add_argument("--perceptual_weight", type=float, default=1.0)
    loss.add_argument("--use_actnorm", action="store_true")

    train = ap.add_argument_group("training")
    train.add_argument("--epochs", type=int, default=20)
    train.add_argument("--batch_size", type=int, default=16)
    train.add_argument("--base_lr", type=float, default=4.5e-6,
                       help="scaled by batch size (taming main.py:530-541)")
    train.add_argument("--absolute_lr", type=float, default=None)
    train.add_argument("--output_dir", type=str, default="./vqgan_ckpt")
    train.add_argument("--save_every_steps", type=int, default=1000)
    train.add_argument("--keep_n_checkpoints", type=int, default=None)
    train.add_argument("--resume", action="store_true")
    train.add_argument("--seed", type=int, default=42)
    train.add_argument("--steps", type=int, default=None)
    train.add_argument("--scan_steps", type=int, default=1,
                       help="k optimizer steps per device dispatch (a NaN "
                            "rollback rewinds the whole k-step group)")
    train.add_argument("--no_preflight", action="store_true")
    train.add_argument("--sample_every_steps", type=int, default=0,
                       help="write original/recon grids (taming ImageLogger "
                            "parity, taming/main.py:215-313)")
    train.add_argument("--sample_dir", type=str, default="./vqgan_samples")
    train.add_argument("--wandb", action="store_true")
    train.add_argument("--wandb_project", type=str, default="vqgan_train")
    train.add_argument("--wandb_name", type=str, default=None)
    train.add_argument("--log_artifacts", action="store_true")

    add_overlap_args(ap)
    add_health_args(ap)
    add_resilience_args(ap)
    add_compile_cache_args(ap)
    add_profiler_args(ap)
    from dalle_tpu.parallel import wrap_arg_parser
    wrap_arg_parser(ap)
    return ap


def main(argv=None):
    args = build_parser().parse_args(argv)
    if not (args.image_folder or args.synthetic):
        print("error: provide --image_folder or --synthetic", file=sys.stderr)
        return 2

    enable_compile_cache(args)
    install_sigusr2_profiler(os.path.join(args.output_dir, "profile"),
                             args)
    import numpy as np
    from dalle_tpu.config import (ObsConfig, OptimConfig, TrainConfig,
                                  VQGANConfig)
    from dalle_tpu.models.gan import GANLossConfig
    from dalle_tpu.parallel import set_backend_from_args
    from dalle_tpu.train.trainer_vqgan import VQGANTrainer

    backend = set_backend_from_args(args).initialize()
    backend.check_batch_size(args.batch_size)
    is_root = backend.is_root_worker()

    lr = args.absolute_lr or args.base_lr * args.batch_size
    model_cfg = VQGANConfig(
        resolution=args.resolution, n_embed=args.n_embed,
        embed_dim=args.embed_dim, z_channels=args.z_channels, ch=args.ch,
        ch_mult=tuple(int(x) for x in args.ch_mult.split(",")),
        num_res_blocks=args.num_res_blocks,
        attn_resolutions=tuple(int(x) for x in args.attn_resolutions.split(",")),
        dropout=args.dropout, quantizer="gumbel" if args.gumbel else "vq")
    loss_cfg = GANLossConfig(
        disc_start=args.disc_start, disc_weight=args.disc_weight,
        disc_num_layers=args.disc_num_layers, disc_ndf=args.disc_ndf,
        disc_loss=args.disc_loss, codebook_weight=args.codebook_weight,
        perceptual_weight=args.perceptual_weight, use_actnorm=args.use_actnorm)
    train_cfg = TrainConfig(
        runtime_lr_scale=args.breach_actions,
        batch_size=args.batch_size, epochs=args.epochs, seed=args.seed,
        checkpoint_dir=args.output_dir, save_every_steps=args.save_every_steps,
        keep_n_checkpoints=args.keep_n_checkpoints,
        preflight_checkpoint=not args.no_preflight,
        sample_every_steps=args.sample_every_steps,
        log_artifacts=args.log_artifacts, scan_steps=args.scan_steps,
        **overlap_train_kwargs(args),
        obs=ObsConfig(**health_obs_kwargs(args)),
        # taming: Adam(lr, betas=(0.5, 0.9)) for both nets (vqgan.py:121-131)
        optim=OptimConfig(learning_rate=lr, beta1=0.5, beta2=0.9,
                          grad_clip_norm=0.0))
    install_health_recorder(args, os.path.join(args.output_dir,
                                               "health_bundles"))

    trainer = VQGANTrainer(model_cfg, train_cfg, loss_cfg=loss_cfg,
                           backend=backend)
    if args.resume:
        trainer.restore()

    # images in [-1, 1] (taming data convention, taming/data/base.py:45-50)
    if args.synthetic:
        from dalle_tpu.data.synthetic import ShapesDataset, batch_iterator
        ds = ShapesDataset(image_size=args.resolution)
        raw = batch_iterator(ds, args.batch_size, seed=args.seed,
                             epochs=args.epochs)
        batches = ((imgs * 2.0 - 1.0,) for imgs, _caps in raw)
    else:
        from dalle_tpu.data.loaders import ImageFolderDataset, batch_arrays
        ds = ImageFolderDataset(args.image_folder, image_size=args.resolution)
        rng = np.random.RandomState(args.seed)

        def folder_batches():
            for _ in range(args.epochs):
                order = rng.permutation(len(ds))
                for s in range(0, len(order) - args.batch_size + 1,
                               args.batch_size):
                    imgs, _ = batch_arrays(ds, order[s:s + args.batch_size])
                    yield (imgs * 2.0 - 1.0,)
        batches = folder_batches()

    if is_root:
        print(f"VQGAN {'gumbel' if args.gumbel else 'vq'}: "
              f"{model_cfg.to_json()}")
    log = print if is_root else (lambda *a, **k: None)

    from dalle_tpu.train.metrics import MetricsLogger
    metrics_writer = None
    if is_root:
        metrics_writer = MetricsLogger(
            path=os.path.join(args.output_dir, "metrics.jsonl"),
            use_wandb=args.wandb, project=args.wandb_project,
            run_name=args.wandb_name, config={"model": model_cfg.to_dict()})

    # original/recon grids (taming ImageLogger parity, main.py:215-313)
    sample_fn = None
    if args.sample_every_steps:
        os.makedirs(args.sample_dir, exist_ok=True)
        if args.synthetic:
            probe = ds.as_arrays(limit=4)[0] * 2.0 - 1.0
        else:
            probe, _ = batch_arrays(ds, list(range(min(4, len(ds)))))
            probe = probe * 2.0 - 1.0

        def sample_fn(step):
            from PIL import Image
            recon = np.asarray(trainer.reconstruct(probe))
            grid = np.concatenate([np.concatenate(list(probe), 1),
                                   np.concatenate(list(recon), 1)], 0)
            grid = ((grid + 1) * 127.5).clip(0, 255).astype("uint8")
            Image.fromarray(grid).save(
                os.path.join(args.sample_dir, f"step{step}_recon.png"))
            if metrics_writer is not None:
                metrics_writer.log_images(step, (recon + 1) * 0.5,
                                          key="reconstructions")
            log(f"[step {step}] recon grid → {args.sample_dir}")

    install_resilience(args, trainer, log=log)
    trainer.fit(batches, steps=args.steps, log=log, sample_fn=sample_fn,
                metrics_writer=metrics_writer)
    if metrics_writer is not None:
        metrics_writer.close()

    final = int(trainer.state.step)
    if trainer.ckpt.latest_step() != final:
        trainer.ckpt.save(final, trainer.state, trainer._meta())
    trainer.ckpt.wait_until_finished()   # final step durable before exit
    if is_root:
        print(f"done at step {final}; checkpoints in {args.output_dir}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
