#!/usr/bin/env python
"""Execute .github/workflows/ci.yml's test-job steps locally (VERDICT r4 #7).

No GitHub runner or container runtime exists in this sandbox, so the
workflow can't run under act/docker. This harness is the honest substitute:
it PARSES the workflow (so a YAML/step regression fails here) and executes
each `run` step of the `test` job verbatim with the job's env — except
steps that need the network (pip installs), which are SKIPPED with a
recorded reason. A green run proves the workflow's commands are executable
as written against this checkout.

Run: python scripts/ci_local.py   (the workflow's pytest step already runs
the fast tier — pyproject addopts default to -m "not slow")
"""

import argparse
import os
import subprocess
import sys

import yaml

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NETWORK_MARKERS = ("pip install", "apt-get", "curl ", "wget ")


def main():
    ap = argparse.ArgumentParser()
    args = ap.parse_args()

    wf = yaml.safe_load(open(os.path.join(ROOT, ".github/workflows/ci.yml")))
    job = wf["jobs"]["test"]
    failures = 0
    for step in job["steps"]:
        name = step.get("name", step.get("uses", "<unnamed>"))
        if "run" not in step:
            print(f"-- [skip] {name}: action step (no local runner)")
            continue
        cmd = step["run"]
        if any(m in cmd for m in NETWORK_MARKERS):
            # the editable-install smoke is half network, half local: keep
            # the local import check. Join backslash continuations first so
            # a continued pip line is dropped whole, and drop comments.
            joined = cmd.replace("\\\n", " ")
            local_lines = [ln for ln in joined.splitlines()
                           if ln.strip() and not ln.strip().startswith("#")
                           and not any(m in ln for m in NETWORK_MARKERS)]
            if not local_lines:
                print(f"-- [skip] {name}: needs network (pip)")
                continue
            cmd = "\n".join(local_lines)
            print(f"-- [trim] {name}: network lines skipped, running rest")
        env = dict(os.environ)
        env.update({k: str(v) for k, v in (step.get("env") or {}).items()})
        print(f"== [run] {name}: {cmd!r}")
        r = subprocess.run(cmd, shell=True, cwd=ROOT, env=env)
        if r.returncode != 0:
            # fail fast like the Actions job would: later steps never run
            # after a failing one, so executing them here would diverge
            # from the workflow being validated (and burn the 1-core box)
            print(f"!! step failed: {name} (exit {r.returncode}) — "
                  "remaining steps skipped (Actions fail-fast semantics)")
            failures += 1
            break
    print("ci_local:", "FAILED" if failures else "GREEN",
          f"({failures} failing steps)" if failures else "")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
