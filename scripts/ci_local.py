#!/usr/bin/env python
"""Execute .github/workflows/ci.yml's test-job steps locally (VERDICT r4 #7).

No GitHub runner or container runtime exists in this sandbox, so the
workflow can't run under act/docker. This harness is the honest substitute:
it PARSES the workflow (so a YAML/step regression fails here) and executes
each `run` step of the `test` job verbatim with the job's env — except
steps that need the network (pip installs), which are SKIPPED with a
recorded reason. A green run proves the workflow's commands are executable
as written against this checkout.

Run: python scripts/ci_local.py   (the workflow's pytest step already runs
the fast tier — pyproject addopts default to -m "not slow")

The graftlint stage runs FIRST, before any workflow step: static findings
are cheaper than a test tier, so they should gate it. --changed-only
narrows the lint to files with UNCOMMITTED changes vs HEAD (the fast
mid-edit loop) — after a commit it lints nothing, so the pre-push / CI
gate is the default full lint. The graftir contract stage follows (IR-level
drift is cheaper to surface than a test tier). The workflow's own
lint/ir_audit steps are skipped here to avoid running each pass twice.
"""

import argparse
import os
import subprocess
import sys

import yaml

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NETWORK_MARKERS = ("pip install", "apt-get", "curl ", "wget ")


def run_lint_stage(changed_only: bool) -> int:
    """The graftlint stage. Returns the lint exit code."""
    cmd = [sys.executable, os.path.join(ROOT, "scripts", "lint.py")]
    if changed_only:
        cmd.append("--changed-only")
    print(f"== [lint] {' '.join(cmd[1:])}")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(cmd, cwd=ROOT, env=env).returncode


def run_ir_audit_stage() -> int:
    """The graftir stage: rebuild every registered entry point's live
    program contract (tracing; compiling the trainer/serve entries for
    collectives + donation aliasing) and diff against the goldens under
    contracts/. Drift fails with the human-readable report; the report +
    drift.json land in ./ir_artifacts — the dir ci.yml uploads
    (scripts/ir_audit.py; the workflow's matching step is skipped below)."""
    cmd = [sys.executable, os.path.join(ROOT, "scripts", "ir_audit.py"),
           "--check", "--report", os.path.join(ROOT, "ir_artifacts")]
    print(f"== [graftir] {' '.join(cmd[1:])}")
    return subprocess.run(cmd, cwd=ROOT).returncode


def run_precision_audit_stage() -> int:
    """The graftnum stage: trace every registered entry point and run the
    precision-flow analysis (low-precision accumulation, int8 matmul
    accumulator width, dequant scale discipline, double rounding, orphaned
    scales — analysis/precision_flow.py). Findings name file::function and
    fail the stage; waivers are '# graftir: allow=precision -- why' source
    comments. The per-entry quantization boundary map + report land in
    ./precision_artifacts — the dir ci.yml uploads alongside ir_artifacts
    (scripts/precision_audit.py; the workflow's matching step is skipped
    below)."""
    cmd = [sys.executable, os.path.join(ROOT, "scripts",
                                        "precision_audit.py"),
           "--report", os.path.join(ROOT, "precision_artifacts")]
    print(f"== [graftnum] {' '.join(cmd[1:])}")
    return subprocess.run(cmd, cwd=ROOT).returncode


def run_sync_audit_stage() -> int:
    """The graftsync stage: the whole-module static concurrency model over
    the threaded control plane — guarded-field/lockset violations,
    acquisition-order cycles, blocking calls under a lock, thread-lifecycle
    hygiene — plus drift of the lock-acquisition graph against the golden
    in contracts/sync.json (scripts/sync_audit.py; the workflow's matching
    step is skipped below). Waivers are '# graftsync: allow=<rule> -- why'
    source comments. Report + findings + SARIF land in ./sync_artifacts —
    the dir ci.yml uploads. The runtime half runs inside the gateway/fleet
    smokes (obs/lockorder.py cross-checks the observed graph)."""
    cmd = [sys.executable, os.path.join(ROOT, "scripts", "sync_audit.py"),
           "--check", "--report", os.path.join(ROOT, "sync_artifacts")]
    print(f"== [graftsync] {' '.join(cmd[1:])}")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(cmd, cwd=ROOT, env=env).returncode


def run_wire_audit_stage() -> int:
    """The graftwire stage: the cross-process wire-protocol model over the
    fleet RPC (sender vs receiver field schemas per verb, verb dispatch
    symmetry, request/replica lifecycle machines vs emitted events —
    analysis/wire_flow.py + rules_wire.py) plus drift of the protocol
    against the golden in contracts/wire.json (scripts/wire_audit.py; the
    workflow's matching step is skipped below). Waivers are
    '# graftwire: allow=<rule> -- why' source comments. Report + findings +
    SARIF land in ./wire_artifacts — the dir ci.yml uploads. The runtime
    half runs inside the gateway/fleet smokes (obs/wiretap.py asserts
    every observed frame ⊆ the golden)."""
    cmd = [sys.executable, os.path.join(ROOT, "scripts", "wire_audit.py"),
           "--check", "--report", os.path.join(ROOT, "wire_artifacts")]
    print(f"== [graftwire] {' '.join(cmd[1:])}")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(cmd, cwd=ROOT, env=env).returncode


def run_obs_smoke_stage() -> int:
    """The grafttrace + host-overlap + graftpulse smoke stage: a short
    synthetic traced fit (device prefetch + async checkpointing + deferred
    metrics + model-health taps ON) that must produce a well-formed
    Perfetto trace, the step-time breakdown AND health/* columns in the
    metrics JSONL, steady-state batch_wait+sync ≈ 0 with the taps fused
    in, a bounded checkpoint-boundary step, a quiet watchdog, <1% span
    overhead, the no-host-transfer/scalar-all-reduce-only tap contract
    (pinned goldens + a live health-on/off probe), and the injected
    codebook collapse → exactly one flight bundle + MODEL-HEALTH DEGRADED
    verdict (scripts/obs_smoke.py; the workflow's matching step is skipped
    below). Artifacts (incl. breakdown.json + health_artifacts/) land in
    ./obs_artifacts — the dir ci.yml uploads."""
    cmd = [sys.executable, os.path.join(ROOT, "scripts", "obs_smoke.py"),
           "--outdir", os.path.join(ROOT, "obs_artifacts")]
    print(f"== [obs] {' '.join(cmd[1:])}")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(cmd, cwd=ROOT, env=env).returncode


def run_serve_smoke_stage() -> int:
    """The continuous-batching serve stage: a short offered-load run that
    must keep slot occupancy ≥ 90% while the queue is nonempty, produce
    token-exact outputs vs the sequential single-request reference for
    every request, and leave valid per-request TTFT/latency spans
    (scripts/serve_smoke.py; the workflow's matching step is skipped
    below). Artifacts land in ./serve_artifacts — the dir ci.yml
    uploads."""
    cmd = [sys.executable, os.path.join(ROOT, "scripts", "serve_smoke.py"),
           "--outdir", os.path.join(ROOT, "serve_artifacts")]
    print(f"== [serve] {' '.join(cmd[1:])}")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(cmd, cwd=ROOT, env=env).returncode


def run_gateway_smoke_stage() -> int:
    """The serving-gateway stage: a loopback HTTP/SSE gateway over two tiny
    replicas — one streamed request end-to-end (SSE grid rows, bitwise
    token-exact vs single-request generation), concurrent multi-tenant
    traffic, quota exhaustion → 429, and the AOT cold-start path serving
    with zero backend compiles (scripts/gateway_smoke.py; the workflow's
    matching step is skipped below). Artifacts land in ./gateway_artifacts
    — the dir ci.yml uploads alongside serve_artifacts."""
    cmd = [sys.executable, os.path.join(ROOT, "scripts", "gateway_smoke.py"),
           "--outdir", os.path.join(ROOT, "gateway_artifacts")]
    print(f"== [gateway] {' '.join(cmd[1:])}")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(cmd, cwd=ROOT, env=env).returncode


def run_fleet_smoke_stage() -> int:
    """The graftfleet stage: a real cross-process replica fleet on
    loopback (scripts/fleet_smoke.py; docs/SERVING.md "Deployment
    topology") — an overload burst breaches the burn-rate sentry and the
    controller attaches a warm AOT-prespawned replica process with ZERO
    backend compiles while goodput recovers; a health-page drain migrates
    a mid-stream request bitwise-invisibly; a chaos-SIGKILLed replica
    process fails over (reason-labeled) and is replaced off missed
    heartbeats; hysteresis/cooldown hold the fleet still under oscillating
    load; and the episode lands as fleet_action events + the obs_report
    FLEET verdict. Artifacts (controller decision log, metrics, flight
    bundles, replica logs) land in ./fleet_artifacts — the dir ci.yml
    uploads (the workflow's matching step is skipped below)."""
    cmd = [sys.executable, os.path.join(ROOT, "scripts", "fleet_smoke.py"),
           "--outdir", os.path.join(ROOT, "fleet_artifacts")]
    print(f"== [fleet] {' '.join(cmd[1:])}")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(cmd, cwd=ROOT, env=env).returncode


def run_chaos_smoke_stage() -> int:
    """The graftmend chaos stage: scripted fault scenarios over the real
    2-process gloo/DCN path (scripts/chaos_smoke.py; docs/RESILIENCE.md)
    — kill a worker mid-step and assert BITWISE-exact recovery vs an
    uninterrupted reference, SIGTERM graceful preemption, injected
    coordinator/checkpoint I/O faults absorbed by the retry layer (not
    crashes), corruption fallback, and an elastic shrink with resharding
    restore. Per-scenario verdicts + agent event logs + flight bundles
    land in ./chaos_artifacts — the dir ci.yml uploads (the workflow's
    matching step is skipped below). Heavy liveness-timeout scenarios stay
    behind --heavy / the slow test tier."""
    cmd = [sys.executable, os.path.join(ROOT, "scripts", "chaos_smoke.py"),
           "--outdir", os.path.join(ROOT, "chaos_artifacts")]
    print(f"== [chaos] {' '.join(cmd[1:])}")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(cmd, cwd=ROOT, env=env).returncode


def run_bench_check_stage() -> None:
    """ADVISORY perf-regression sentry: diff the newest BENCH_r*/
    MULTICHIP_r* round against the prior one with a tolerance band
    (scripts/bench_check.py). Advisory because this sandbox's CPU-mesh
    numbers jitter with box load — a REGRESSED verdict is a prompt to
    look at the diff, not a build failure (run with --strict on real
    hardware). The stage therefore never gates the test tiers."""
    cmd = [sys.executable, os.path.join(ROOT, "scripts", "bench_check.py")]
    print(f"== [bench_check, advisory] {' '.join(cmd[1:])}")
    r = subprocess.run(cmd, cwd=ROOT)
    if r.returncode != 0:
        print("ci_local: bench_check reported issues (ADVISORY — not "
              "gating)")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--changed-only", action="store_true",
                    help="git-diff-scope the lint stage (fast pre-push loop)")
    args = ap.parse_args()

    if run_lint_stage(args.changed_only) != 0:
        print("ci_local: FAILED (lint stage) — test tiers not run")
        return 1

    rc = run_ir_audit_stage()
    if rc == 3:
        # the audit's distinct missing-golden code: a NEW entry point
        # without a golden, not drift in any pinned program
        print("ci_local: FAILED (graftir goldens MISSING — new entry "
              "point? run scripts/ir_audit.py --update and commit) — "
              "test tiers not run")
        return 1
    if rc != 0:
        print("ci_local: FAILED (graftir contract drift) — test tiers not run")
        return 1

    if run_precision_audit_stage() != 0:
        print("ci_local: FAILED (graftnum precision findings) — test tiers "
              "not run")
        return 1

    rc = run_sync_audit_stage()
    if rc == 3:
        print("ci_local: FAILED (graftsync golden lock graph MISSING — "
              "run scripts/sync_audit.py --update and commit "
              "contracts/sync.json) — test tiers not run")
        return 1
    if rc != 0:
        print("ci_local: FAILED (graftsync concurrency findings / lock-"
              "graph drift) — test tiers not run")
        return 1

    rc = run_wire_audit_stage()
    if rc == 3:
        print("ci_local: FAILED (graftwire golden protocol contract "
              "MISSING — run scripts/wire_audit.py --update and commit "
              "contracts/wire.json) — test tiers not run")
        return 1
    if rc != 0:
        print("ci_local: FAILED (graftwire protocol findings / contract "
              "drift) — test tiers not run")
        return 1

    if run_obs_smoke_stage() != 0:
        print("ci_local: FAILED (observability smoke) — test tiers not run")
        return 1

    if run_serve_smoke_stage() != 0:
        print("ci_local: FAILED (serve smoke) — test tiers not run")
        return 1

    if run_gateway_smoke_stage() != 0:
        print("ci_local: FAILED (gateway smoke) — test tiers not run")
        return 1

    if run_fleet_smoke_stage() != 0:
        print("ci_local: FAILED (fleet smoke) — test tiers not run")
        return 1

    if run_chaos_smoke_stage() != 0:
        print("ci_local: FAILED (chaos smoke) — test tiers not run")
        return 1

    run_bench_check_stage()

    wf = yaml.safe_load(open(os.path.join(ROOT, ".github/workflows/ci.yml")))
    job = wf["jobs"]["test"]
    failures = 0
    for step in job["steps"]:
        name = step.get("name", step.get("uses", "<unnamed>"))
        if "run" not in step:
            print(f"-- [skip] {name}: action step (no local runner)")
            continue
        cmd = step["run"]
        if "scripts/lint.py" in cmd:
            print(f"-- [skip] {name}: already run in the lint stage")
            continue
        if "scripts/ir_audit.py" in cmd:
            print(f"-- [skip] {name}: already run in the graftir stage")
            continue
        if "scripts/precision_audit.py" in cmd:
            print(f"-- [skip] {name}: already run in the graftnum stage")
            continue
        if "scripts/sync_audit.py" in cmd:
            print(f"-- [skip] {name}: already run in the graftsync stage")
            continue
        if "scripts/wire_audit.py" in cmd:
            print(f"-- [skip] {name}: already run in the graftwire stage")
            continue
        if "scripts/obs_smoke.py" in cmd:
            print(f"-- [skip] {name}: already run in the obs smoke stage")
            continue
        if "scripts/serve_smoke.py" in cmd:
            print(f"-- [skip] {name}: already run in the serve smoke stage")
            continue
        if "scripts/gateway_smoke.py" in cmd:
            print(f"-- [skip] {name}: already run in the gateway smoke "
                  "stage")
            continue
        if "scripts/fleet_smoke.py" in cmd:
            print(f"-- [skip] {name}: already run in the fleet smoke stage")
            continue
        if "scripts/chaos_smoke.py" in cmd:
            print(f"-- [skip] {name}: already run in the chaos smoke stage")
            continue
        if "scripts/bench_check.py" in cmd:
            print(f"-- [skip] {name}: already run in the bench_check stage")
            continue
        if any(m in cmd for m in NETWORK_MARKERS):
            # the editable-install smoke is half network, half local: keep
            # the local import check. Join backslash continuations first so
            # a continued pip line is dropped whole, and drop comments.
            joined = cmd.replace("\\\n", " ")
            local_lines = [ln for ln in joined.splitlines()
                           if ln.strip() and not ln.strip().startswith("#")
                           and not any(m in ln for m in NETWORK_MARKERS)]
            if not local_lines:
                print(f"-- [skip] {name}: needs network (pip)")
                continue
            cmd = "\n".join(local_lines)
            print(f"-- [trim] {name}: network lines skipped, running rest")
        env = dict(os.environ)
        env.update({k: str(v) for k, v in (step.get("env") or {}).items()})
        print(f"== [run] {name}: {cmd!r}")
        r = subprocess.run(cmd, shell=True, cwd=ROOT, env=env)
        if r.returncode != 0:
            # fail fast like the Actions job would: later steps never run
            # after a failing one, so executing them here would diverge
            # from the workflow being validated (and burn the 1-core box)
            print(f"!! step failed: {name} (exit {r.returncode}) — "
                  "remaining steps skipped (Actions fail-fast semantics)")
            failures += 1
            break
    print("ci_local:", "FAILED" if failures else "GREEN",
          f"({failures} failing steps)" if failures else "")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
