"""Shared dispatched-scan timing harness for the bench/profile scripts.

Methodology (see NEXT.md environment notes): the axon tunnel costs ~20ms per
dispatch, so a candidate is timed as K executions inside ONE jitted
`lax.scan`. Two traps this helper exists to avoid (they bit real tables):

  * Loop hoisting — every *floating* argument is perturbed by the scan carry
    so XLA cannot compute the body once outside the loop. Integer args can't
    be perturbed: anything whose gradient/recompute matters must be passed
    as a floating argument, not closed over (closures are jit constants).
  * Dead-code elimination of backward work — grad wrt a subset of inputs
    lets XLA drop the other cotangents' matmuls (e.g. dk/dv of dense
    attention), biasing comparisons against opaque custom_vjp kernels that
    always compute the full backward. ``grad_argnums`` defaults to ALL
    floating arguments.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp


def _is_float(x):
    return jnp.issubdtype(jnp.result_type(x), jnp.floating)


def timed_scan(fn, args, k: int = 8, grad: bool = False, grad_argnums=None):
    """Seconds per execution of ``fn(*args)`` (or its full backward when
    ``grad=True``), amortized over k in-program iterations.

    ``fn`` must return an array or pytree; the loss for grad mode is the
    sum of squares of all output leaves (f32). ``grad_argnums`` defaults to
    every floating positional argument — pass a tuple to restrict.
    """
    if grad:
        if grad_argnums is None:
            grad_argnums = tuple(i for i, a in enumerate(args)
                                 if jax.tree.all(jax.tree.map(_is_float, a)))

        def scalar_loss(*a):
            out = fn(*a)
            return sum(jnp.sum(leaf.astype(jnp.float32) ** 2)
                       for leaf in jax.tree.leaves(out))

        base = jax.grad(scalar_loss, argnums=grad_argnums)
    else:
        base = fn

    @jax.jit
    def many(args):
        def body(c, _):
            perturbed = tuple(
                jax.tree.map(
                    lambda x: x + jnp.asarray(1e-12 * c, x.dtype)
                    if _is_float(x) else x, a)
                for a in args)
            out = base(*perturbed)
            s = sum(jnp.sum(leaf.astype(jnp.float32))
                    for leaf in jax.tree.leaves(out))
            return c + 1e-30 * s, None

        c, _ = jax.lax.scan(body, jnp.float32(0.0), None, length=k)
        return c

    float(jax.device_get(many(args)))       # compile + hard sync
    t0 = time.perf_counter()
    float(jax.device_get(many(args)))
    return (time.perf_counter() - t0) / k
