#!/usr/bin/env python
"""Serving gateway CLI: HTTP/SSE front end over a replica fleet.

Runs N continuous-batching replicas (dalle_tpu/serve) behind the gateway
(dalle_tpu/gateway): per-tenant token-bucket quotas, SLO-aware admission,
priority/deadline scheduling, queue-depth-aware dispatch with mid-stream
failover, graceful drain on SIGINT/SIGTERM. See docs/SERVING.md.

A trained checkpoint serves real traffic:
  python scripts/serve_gateway.py --dalle_path ./checkpoints/dalle \
      --replicas 2 --slots 8 --port 8080

AOT cold-start workflow (replica up in seconds, no retrace):
  python scripts/serve_gateway.py --dalle_path ... --aot_export ./aot  # once
  python scripts/serve_gateway.py --dalle_path ... --aot_dir ./aot     # cold

--untrained runs a tiny random model on loopback (smoke/demo, no assets).
"""

import argparse
import json
import os
import signal
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import (add_compile_cache_args, add_profiler_args,  # noqa: E402,E501
                     enable_compile_cache, install_sigusr2_profiler,
                     load_model_checkpoint, load_vae_sidecar)


def build_parser():
    ap = argparse.ArgumentParser(description=__doc__)
    src = ap.add_argument_group("model")
    src.add_argument("--dalle_path", type=str, default=None,
                     help="DALLE checkpoint dir (scripts/train_dalle.py)")
    src.add_argument("--untrained", action="store_true",
                     help="tiny random model (loopback smoke/demo)")
    src.add_argument("--clip_path", type=str, default=None,
                     help="CLIP checkpoint dir (scripts/train_clip.py) to "
                          "attach as the /v1/images reranker — restored "
                          "params-only, no training imports "
                          "(models/clip.py load_clip)")
    src.add_argument("--precision", type=str, default="int8w",
                     choices=["float32", "bfloat16", "bf16_int8kv", "int8w"],
                     help="serve-engine precision (int8w = the audited "
                          "minimum-HBM default)")
    fleet = ap.add_argument_group("fleet")
    fleet.add_argument("--replicas", type=int, default=1)
    fleet.add_argument("--slots", type=int, default=4,
                       help="decode slots (device batch) per replica")
    fleet.add_argument("--steps_per_sync", type=int, default=4,
                       help="device steps per host sync (amortizes "
                            "dispatch; a freed slot waits up to K-1 steps)")
    fleet.add_argument("--queue_maxsize", type=int, default=64,
                       help="bounded per-replica backlog; overflow → 429")
    fleet.add_argument("--prefill_chunk", type=int, default=0,
                       help="split window and trickle prefills into chunks "
                            "of this many positions, interleaved with decode "
                            "iterations (p95 TTFT isolation for long "
                            "prompts; shared-prefix cohort prefills stay "
                            "one-shot; 0 = one-shot prefills, the default — "
                            "required for --aot_dir/--aot_export)")
    fleet.add_argument("--policy", type=str, default="fifo",
                       choices=["fifo", "priority_deadline"],
                       help="take-order policy (fifo = pinned default; "
                            "priority_deadline adds tiers + EDF + shedding)")
    aot = ap.add_argument_group("AOT cold start (docs/SERVING.md)")
    aot.add_argument("--aot_dir", type=str, default=None,
                     help="load serialized engine executables (cold-start "
                          "without retrace/recompile; fingerprint-checked)")
    aot.add_argument("--aot_export", type=str, default=None,
                     help="compile + serialize the engine programs to this "
                          "dir and exit (run once per config/topology)")
    net = ap.add_argument_group("network / quotas")
    net.add_argument("--host", type=str, default="127.0.0.1")
    net.add_argument("--port", type=int, default=8080)
    net.add_argument("--tenant_rate", type=float, default=10.0,
                     help="default per-tenant requests/s")
    net.add_argument("--tenant_burst", type=float, default=20.0)
    net.add_argument("--tenant_override", action="append", default=[],
                     metavar="TENANT=RATE:BURST",
                     help="per-tenant quota override (repeatable)")
    ap.add_argument("--prometheus_path", type=str, default="",
                    help="node-exporter textfile target (written on drain; "
                         "live scrape is GET /metrics)")
    scope = ap.add_argument_group("graftscope (docs/OBSERVABILITY.md)")
    scope.add_argument("--flight_dir", type=str, default="flight_bundles",
                       help="flight-recorder bundle dir ('off' disables); "
                            "bundles dump on replica death, failover, SLO "
                            "breach, watchdog stall and SIGQUIT")
    scope.add_argument("--slo_objective", type=float, default=0.999,
                       help="availability objective for the burn-rate "
                            "sentry (error budget = 1 - objective)")
    scope.add_argument("--usage_log", type=str, default=None,
                       help="graftlens per-tenant usage ledger: append-only "
                            "JSONL (one record per completion: tenant, "
                            "trace_id, token counts, queue wait) with "
                            "size-based atomic rotation; the "
                            "usage.*_total{tenant=} counters are always on")
    scope.add_argument("--decode_health", action="store_true",
                       help="graftpulse decode-quality gauges: per-request "
                            "token entropy / top-k mass / repeated-token "
                            "ratio from logits already on device (zero "
                            "added host syncs; tokens stay bit-exact). "
                            "Program-shaping: pair with a matching "
                            "--aot_export")
    add_compile_cache_args(ap)
    add_profiler_args(ap)
    return ap


def build_wrapper(args):
    import jax
    from dalle_tpu.models.wrapper import DalleWithVae
    if args.untrained:
        from dalle_tpu.config import DalleConfig
        from dalle_tpu.models.dalle import init_dalle
        cfg = DalleConfig(num_text_tokens=32, text_seq_len=6, dim=64,
                          depth=2, heads=2, dim_head=32, image_size=16,
                          image_vocab_size=24, image_fmap_size=4)
        model, params = init_dalle(cfg, jax.random.PRNGKey(0), batch=2)
        return DalleWithVae(model, params, None)
    if not args.dalle_path:
        raise SystemExit("provide --dalle_path or --untrained")
    from dalle_tpu.config import DalleConfig
    from dalle_tpu.models.dalle import init_dalle
    model, params, _ = load_model_checkpoint(args.dalle_path, "DALLE",
                                             DalleConfig, init_dalle)
    vae = load_vae_sidecar(args.dalle_path)
    return DalleWithVae(model, params, vae)


def attach_clip(dv, args):
    if not args.clip_path:
        return dv
    from dalle_tpu.models.clip import load_clip
    clip_model, clip_params = load_clip(args.clip_path)
    print(f"rerank: CLIP attached from {args.clip_path}")
    return dv.attach_rerank(clip_model, clip_params)


def main(argv=None):
    args = build_parser().parse_args(argv)
    enable_compile_cache(args)
    install_sigusr2_profiler("profile_artifacts", args)

    from dalle_tpu import obs
    from dalle_tpu.gateway import (AdmissionController, Gateway, Replica,
                                   ReplicaRouter, TenantQuotas,
                                   save_engine_aot)
    from dalle_tpu.serve import PriorityDeadlinePolicy

    obs.configure()
    if args.flight_dir != "off":
        # the serving black box: low-rate state sampling in steady state,
        # an atomic post-mortem bundle on replica death / failover / SLO
        # breach / watchdog stall / SIGQUIT (docs/OBSERVABILITY.md)
        obs.configure_recorder(args.flight_dir, sample_interval_s=1.0)
        obs.install_signal_dump()
    dv = attach_clip(build_wrapper(args), args)

    def make_engine():
        return dv.serve_engine(slots=args.slots, precision=args.precision,
                               steps_per_sync=args.steps_per_sync,
                               decode_health=args.decode_health,
                               prefill_chunk=args.prefill_chunk)

    if args.aot_export:
        manifest = save_engine_aot(make_engine(), args.aot_export)
        print(json.dumps({"aot_export": args.aot_export,
                          "payload_bytes": manifest["payload_bytes"]}))
        return 0

    policy_cls = (PriorityDeadlinePolicy if args.policy ==
                  "priority_deadline" else None)
    overrides = {}
    for spec in args.tenant_override:
        tenant, _, rb = spec.partition("=")
        rate, _, burst = rb.partition(":")
        overrides[tenant] = (float(rate), float(burst or rate))
    from dalle_tpu.gateway import SloEstimator
    admission = AdmissionController(
        TenantQuotas(args.tenant_rate, args.tenant_burst, overrides),
        # completions observe per-request rate; backlog drains at ~rate ×
        # total slots, so the predictor needs the fleet parallelism
        SloEstimator(parallelism=args.slots * args.replicas))

    replicas = []
    for i in range(args.replicas):
        # estimator feeding moved to the gateway door (server.py
        # _record_outcome): every topology's completions — these local
        # threads AND graftfleet remote processes — warm the admission
        # throughput estimate through one path, so no per-replica
        # on_served wiring here (it would double-count local completions)
        rep = Replica(make_engine(), replica_id=f"replica-{i}",
                      maxsize=args.queue_maxsize,
                      policy=policy_cls() if policy_cls else None,
                      aot_dir=args.aot_dir)
        replicas.append(rep.start())
        print(f"{rep.replica_id}: serving (aot_loaded={rep.aot_loaded})")

    def on_breach(verdict):
        obs.counter_add("slo.breaches_total", 1.0)
        path = obs.dump_recorder("slo_breach", extra={
            "dominating": verdict["dominating"],
            "windows": verdict["windows"]})
        print(f"SLO BURNING (dominating window {verdict['dominating']})"
              + (f"; bundle {path}" if path else ""), flush=True)

    gw = Gateway(ReplicaRouter(replicas), admission,
                 host=args.host, port=args.port, vae=dv.vae, clip=dv.clip,
                 slo_sentry=obs.BurnRateSentry(
                     objective=args.slo_objective, on_breach=on_breach),
                 usage_log=args.usage_log)
    gw.start()
    print(f"gateway listening on {gw.address} "
          f"({args.replicas} replica(s) × {args.slots} slots, "
          f"policy={args.policy}, precision={args.precision})", flush=True)

    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    print("draining…", flush=True)
    gw.shutdown(drain=True)
    if args.prometheus_path:
        obs.write_textfile(args.prometheus_path, obs.metrics_snapshot())
    print("drained; bye")
    return 0


if __name__ == "__main__":
    sys.exit(main())
