#!/usr/bin/env python
"""Train the discrete VAE on TPU (or the CPU mesh).

Reference: legacy/train_vae.py (full distributed flow, SURVEY.md §3.4) and the
fork's vae.py (NaN rollback, best-loss checkpointing). One process per host;
data-parallelism comes from the mesh, not a launcher.

Examples:
  python scripts/sampler.py --outdir /tmp/shapes --count 256 --image_size 64
  python scripts/train_vae.py --image_folder /tmp/shapes --image_size 64 \
      --num_layers 2 --hidden_dim 32 --num_tokens 256 --epochs 2 --batch_size 8
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import (add_compile_cache_args, add_health_args,  # noqa: E402
                     add_resilience_args, install_resilience,
                     add_overlap_args, add_profiler_args,
                     enable_compile_cache, health_obs_kwargs,
                     install_health_recorder, install_sigusr2_profiler,
                     overlap_train_kwargs)


def build_parser():
    ap = argparse.ArgumentParser(description=__doc__)
    data = ap.add_argument_group("data")
    data.add_argument("--image_folder", type=str, default=None,
                      help="folder of images (txt captions ignored for VAE)")
    data.add_argument("--synthetic", action="store_true",
                      help="train on the built-in shapes dataset")

    model = ap.add_argument_group("model")
    model.add_argument("--image_size", type=int, default=128)
    model.add_argument("--num_tokens", type=int, default=8192)
    model.add_argument("--codebook_dim", type=int, default=512)
    model.add_argument("--num_layers", type=int, default=3)
    model.add_argument("--num_resnet_blocks", type=int, default=1)
    model.add_argument("--hidden_dim", type=int, default=64)
    model.add_argument("--smooth_l1_loss", action="store_true")
    model.add_argument("--kl_loss_weight", type=float, default=0.0)
    model.add_argument("--straight_through", action="store_true")

    train = ap.add_argument_group("training")
    train.add_argument("--epochs", type=int, default=20)
    train.add_argument("--batch_size", type=int, default=8)
    train.add_argument("--learning_rate", type=float, default=1e-3)
    train.add_argument("--lr_decay_rate", type=float, default=0.98)
    train.add_argument("--starting_temp", type=float, default=1.0)
    train.add_argument("--temp_min", type=float, default=0.5)
    train.add_argument("--anneal_rate", type=float, default=1e-6)
    train.add_argument("--clip_grad_norm", type=float, default=0.0)
    train.add_argument("--output_dir", type=str, default="./vae_ckpt")
    train.add_argument("--save_every_steps", type=int, default=1000)
    train.add_argument("--keep_n_checkpoints", type=int, default=None)
    train.add_argument("--seed", type=int, default=42)
    train.add_argument("--steps", type=int, default=None,
                       help="hard stop after N steps (overrides epochs)")
    train.add_argument("--scan_steps", type=int, default=1,
                       help="k optimizer steps per device dispatch (a NaN "
                            "rollback rewinds the whole k-step group)")
    train.add_argument("--no_preflight", action="store_true")
    train.add_argument("--sample_every_steps", type=int, default=0,
                       help="log recon grids + codebook histogram every N "
                            "steps (ref legacy/train_vae.py:245-264)")
    train.add_argument("--sample_dir", type=str, default="./vae_samples")
    train.add_argument("--wandb", action="store_true")
    train.add_argument("--wandb_project", type=str, default="dalle_train_vae")
    train.add_argument("--wandb_name", type=str, default=None)
    train.add_argument("--log_artifacts", action="store_true")

    add_overlap_args(ap)
    add_health_args(ap)
    add_resilience_args(ap)
    add_compile_cache_args(ap)
    add_profiler_args(ap)
    from dalle_tpu.parallel import wrap_arg_parser
    wrap_arg_parser(ap)
    return ap


def main(argv=None):
    args = build_parser().parse_args(argv)
    if not args.image_folder and not args.synthetic:
        print("error: provide --image_folder or --synthetic", file=sys.stderr)
        return 2

    enable_compile_cache(args)
    install_sigusr2_profiler(os.path.join(args.output_dir, "profile"),
                             args)
    from dalle_tpu.config import (AnnealConfig, DVAEConfig, ObsConfig,
                                  OptimConfig, TrainConfig)
    from dalle_tpu.parallel import set_backend_from_args
    from dalle_tpu.train.trainer_vae import VAETrainer

    backend = set_backend_from_args(args).initialize()
    backend.check_batch_size(args.batch_size)

    model_cfg = DVAEConfig(
        image_size=args.image_size, num_tokens=args.num_tokens,
        codebook_dim=args.codebook_dim, num_layers=args.num_layers,
        num_resnet_blocks=args.num_resnet_blocks, hidden_dim=args.hidden_dim,
        smooth_l1_loss=args.smooth_l1_loss, kl_div_loss_weight=args.kl_loss_weight,
        straight_through=args.straight_through)
    train_cfg = TrainConfig(
        runtime_lr_scale=args.breach_actions,
        batch_size=args.batch_size, epochs=args.epochs, seed=args.seed,
        checkpoint_dir=args.output_dir, save_every_steps=args.save_every_steps,
        keep_n_checkpoints=args.keep_n_checkpoints,
        preflight_checkpoint=not args.no_preflight,
        sample_every_steps=args.sample_every_steps,
        log_artifacts=args.log_artifacts, scan_steps=args.scan_steps,
        **overlap_train_kwargs(args),
        obs=ObsConfig(**health_obs_kwargs(args)),
        optim=OptimConfig(learning_rate=args.learning_rate,
                          grad_clip_norm=args.clip_grad_norm,
                          lr_scheduler="exponential",
                          lr_decay_rate=args.lr_decay_rate))
    install_health_recorder(args, os.path.join(args.output_dir,
                                               "health_bundles"))
    anneal = AnnealConfig(starting_temp=args.starting_temp,
                          temp_min=args.temp_min, anneal_rate=args.anneal_rate)

    if args.synthetic:
        from dalle_tpu.data.synthetic import ShapesDataset, batch_iterator
        ds = ShapesDataset(image_size=args.image_size)
        batches = batch_iterator(ds, args.batch_size, seed=args.seed,
                                 epochs=args.epochs)
    else:
        from dalle_tpu.data.text_image import TextImageDataset
        ds = TextImageDataset(args.image_folder, image_size=args.image_size,
                              shuffle=True, seed=args.seed, text_from_filename=True)
        batches = ds.batches(args.batch_size, epochs=args.epochs)

    if backend.is_root_worker():
        print(f"dVAE: {model_cfg.to_json()}")
        print(f"dataset: {len(ds)} samples; mesh {dict(backend.mesh.shape)}")

    trainer = VAETrainer(model_cfg, train_cfg, anneal, backend=backend)
    is_root = backend.is_root_worker()
    log = print if is_root else (lambda *a, **k: None)

    from dalle_tpu.train.metrics import MetricsLogger
    metrics_writer = None
    if is_root:
        metrics_writer = MetricsLogger(
            path=os.path.join(args.output_dir, "metrics.jsonl"),
            use_wandb=args.wandb, project=args.wandb_project,
            run_name=args.wandb_name, config={"model": model_cfg.to_dict()})

    # recon grids + codebook-collapse histogram (ref train_vae.py:245-264)
    sample_fn = None
    if args.sample_every_steps:
        import numpy as np
        os.makedirs(args.sample_dir, exist_ok=True)
        probe = next(iter(ds.batches(min(args.batch_size, 8), epochs=1)))[0] \
            if not args.synthetic else ds.as_arrays(limit=8)[0]

        def sample_fn(step):
            recons = np.asarray(trainer.reconstruct(probe, hard=True))
            from PIL import Image
            grid = (np.concatenate([np.concatenate(list(probe), 1),
                                    np.concatenate(list(recons), 1)], 0)
                    * 255).clip(0, 255).astype("uint8")
            Image.fromarray(grid).save(
                os.path.join(args.sample_dir, f"step{step}_recon.png"))
            hist = trainer.codebook_histogram(probe)
            used = int((hist > 0).sum())
            if metrics_writer is not None:
                metrics_writer.log(step, {"codebook_used": used})
                metrics_writer.log_images(step, recons, key="hard_recons")
            log(f"[step {step}] recon grid → {args.sample_dir}; "
                f"codebook codes used: {used}/{model_cfg.num_tokens}")

    install_resilience(args, trainer, log=log)
    trainer.fit(batches, steps=args.steps, log=log, sample_fn=sample_fn,
                metrics_writer=metrics_writer)
    if metrics_writer is not None:
        metrics_writer.close()

    final = int(trainer.state.step)
    if trainer.ckpt.latest_step() != final:  # avoid re-saving an existing step
        # _meta(), not a hand-built dict: extra_meta carries mid-run state
        # (the gumbel re-anneal rebase) that a resume must see
        trainer.ckpt.save(final, trainer.state, trainer._meta())
    trainer.ckpt.wait_until_finished()   # final step durable before exit
    if backend.is_root_worker():
        print(f"done at step {final}; checkpoints in {args.output_dir}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
