#!/usr/bin/env python
"""graftlint CLI — run the repo's static-analysis pass; exit non-zero on
findings.

    python scripts/lint.py                     # whole repo (dalle_tpu, scripts)
    python scripts/lint.py dalle_tpu/ops/x.py  # specific files
    python scripts/lint.py --changed-only      # git-diff-scoped (fast CI stage)
    python scripts/lint.py --list-rules
    python scripts/lint.py --select broad-except,prng-key-reuse

There is deliberately no --fix: every rule here flags a judgment call
(justify the broad except, pick the right key plumbing, recalibrate the
estimator) that an auto-rewriter would get wrong silently.
"""

import argparse
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

# the vmem-ceiling rule imports ops.fused_attention (which imports jax);
# keep that import on CPU so linting never touches an accelerator
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*",
                    help="repo-relative .py files to lint (default: all)")
    ap.add_argument("--changed-only", action="store_true",
                    help="lint only files with uncommitted changes vs HEAD "
                         "(mid-edit loop; after a commit this lints nothing "
                         "— use the full lint as the push gate). Project-"
                         "wide rules still run when their triggers changed")
    ap.add_argument("--select", help="comma-separated rule names to run")
    ap.add_argument("--ignore", help="comma-separated rule names to skip")
    ap.add_argument("--format", choices=("text", "sarif"), default="text",
                    help="finding output format (sarif: a SARIF 2.1.0 "
                         "document on stdout for GitHub PR annotation; "
                         "the text summary moves to stderr)")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    from dalle_tpu.analysis import RULES, run_lint
    from dalle_tpu.analysis.core import to_sarif

    if args.list_rules:
        width = max(len(n) for n in RULES)
        for name, rule in sorted(RULES.items()):
            print(f"{name:<{width}}  {rule.description}")
        return 0

    paths = None
    if args.paths:
        paths = [os.path.relpath(os.path.abspath(p), ROOT).replace(os.sep, "/")
                 for p in args.paths]
        missing = [orig for orig, rel in zip(args.paths, paths)
                   if not os.path.isfile(os.path.join(ROOT, rel))]
        if missing:
            sys.exit(f"lint.py: no such file: {', '.join(missing)}")

    def rule_names(arg, flag):
        if not arg:
            return None
        names = [n.strip() for n in arg.split(",") if n.strip()]
        unknown = [n for n in names if n not in RULES]
        if unknown:
            # a typo'd --select silently running ZERO rules would report
            # green while checking nothing — make it a hard error instead
            sys.exit(f"lint.py: unknown rule(s) for {flag}: "
                     f"{', '.join(unknown)} (see --list-rules)")
        return names

    try:
        findings = run_lint(
            paths=paths,
            select=rule_names(args.select, "--select"),
            ignore=rule_names(args.ignore, "--ignore"),
            changed_only=args.changed_only,
            repo_root=ROOT,
        )
    except RuntimeError as e:   # e.g. --changed-only with git unavailable
        sys.exit(f"lint.py: {e}")
    n = len(findings)
    scope = "changed files" if args.changed_only else "repo"
    summary = f"graftlint: {n} finding{'s' if n != 1 else ''} ({scope})"
    if args.format == "sarif":
        import json
        print(json.dumps(to_sarif(
            findings, "graftlint",
            {name: r.description for name, r in RULES.items()}), indent=1))
        for f in findings:
            print(f, file=sys.stderr)
        print(summary, file=sys.stderr)
    else:
        for f in findings:
            print(f)
        print(summary)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
