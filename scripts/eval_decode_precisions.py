#!/usr/bin/env python
"""Decode fast-path referee: token-exact accuracy + latency of every decode
precision on a TRAINED model.

VERDICT r3 weak #2: the int8 KV cache was only validated on an untrained
model, where near-uniform logits flip argmax under any noise. This script
trains the rainbow pipeline (the reference's own integration bar —
examples/rainbow_dalle.ipynb cells 41-44 token-accuracy metric), then decodes
the SAME captions with the SAME sampling key under each precision mode and
reports token-exact accuracy against the dVAE's codes plus per-batch decode
latency. Accuracy deltas between modes bound the quantization damage on a
model users would actually run.

Modes: f32 | bf16 (weights+KV) | bf16+int8 KV | bf16+int8 weights
(+int8 KV) — the last via ``quantize_params_int8`` (decode matmuls run
int8->bf16 dequant per tile; see ops/quantize_weights.py).

Run (CPU mesh): XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu python scripts/eval_decode_precisions.py --small
Run (TPU, recorded in NEXT.md): python scripts/eval_decode_precisions.py
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def train_rainbow(args, dataset=None):
    """dVAE + DALLE on synthetic shapes; returns (dalle_model, params, text,
    codes, train_idx). ``dataset`` overrides the corpus (same
    __len__/__getitem__→Sample contract as ShapesDataset) — e.g. the
    textured proxy eval_speculative uses to measure acceptance on flatter
    token statistics."""
    import numpy as np
    from dalle_tpu.config import (DVAEConfig, DalleConfig, OptimConfig,
                                  TrainConfig)
    from dalle_tpu.data.loaders import Token
    from dalle_tpu.data.synthetic import ShapesDataset, batch_iterator
    from dalle_tpu.models.wrapper import DiscreteVAEAdapter
    from dalle_tpu.train.trainer_dalle import DalleTrainer
    from dalle_tpu.train.trainer_vae import VAETrainer

    rng = np.random.RandomState(args.seed)
    ds = dataset if dataset is not None else ShapesDataset(
        image_size=args.image_size)
    vcfg = DVAEConfig(image_size=args.image_size, num_tokens=args.num_tokens,
                      codebook_dim=64, num_layers=2, hidden_dim=32,
                      num_resnet_blocks=1)
    tc = TrainConfig(batch_size=args.batch_size,
                     checkpoint_dir=os.path.join(args.outdir, "vae"),
                     log_every=200, metrics_every=20,
                     preflight_checkpoint=False,
                     optim=OptimConfig(learning_rate=2e-3, grad_clip_norm=0.0))
    vt = VAETrainer(vcfg, tc)
    vt.fit(batch_iterator(ds, args.batch_size, seed=args.seed),
           steps=args.vae_steps)
    vae = DiscreteVAEAdapter(vt.model, vt.state.params)

    imgs = np.stack([ds[i].image
                     for i in range(len(ds))]).astype(np.float32) / 255.0
    caps = [ds[i].caption for i in range(len(ds))]
    codes = np.concatenate(
        [np.asarray(vae.get_codebook_indices(imgs[s:s + 64]))
         for s in range(0, len(imgs), 64)])
    tok = Token([c.split() for c in caps])
    seq_len = max(args.pad_text_to or 0, tok.sequence_len)
    text = tok.parse(seq_len=seq_len)

    order = rng.permutation(len(ds))
    n_train = max(int(len(ds) * args.train_frac), args.batch_size)
    tr_idx = order[:n_train]

    dcfg = DalleConfig(num_text_tokens=tok.num_pairs,
                       text_seq_len=seq_len, dim=args.dim,
                       depth=args.depth, heads=4, dim_head=args.dim // 4,
                       image_size=args.image_size,
                       image_vocab_size=args.num_tokens,
                       image_fmap_size=vae.image_fmap_size)
    tc2 = TrainConfig(batch_size=args.batch_size,
                      checkpoint_dir=os.path.join(args.outdir, "dalle"),
                      log_every=200, metrics_every=20,
                      preflight_checkpoint=False,
                      optim=OptimConfig(learning_rate=1e-3,
                                        grad_clip_norm=0.0))
    dt = DalleTrainer(dcfg, tc2)

    def batches():
        while True:
            sel = rng.choice(tr_idx, args.batch_size)
            yield text[sel], codes[sel]

    dt.fit(batches(), steps=args.dalle_steps)
    return dt.model, dt.state.params, text, codes, tr_idx


def decode_hbm_bytes_per_token(cfg, mode: str) -> dict:
    """Analytic decode HBM ledger, bytes per generated token at batch 1 —
    the bandwidth-bound worst case AR decode lives in. Each token streams
    every matmul kernel from HBM once (weights amortize over batch; the KV
    read never does) plus the KV prefix at its average length. Counted:
    the four per-layer kernels (qkv/out/w1/w2), the output head (tied
    table or Dense kernel — same element count), the KV read at mean
    prefix length, and the f32 per-channel scales int8 storage adds.
    Excluded as noise: biases, layernorms, embedding gathers (one row per
    token), KV writes (one position per token).

    ``mode``: f32 | bf16 | bf16_int8kv | int8w_int8kv (the decode_modes
    vocabulary; the fast-topk mode shares bf16_int8kv's bytes)."""
    h, d, dim, depth = cfg.heads, cfg.dim_head, cfg.dim, cfg.depth
    hd = h * d
    mult = getattr(cfg, "ff_mult", 4)
    total_tokens = (cfg.num_text_tokens + cfg.text_seq_len
                    + cfg.image_vocab_size)
    kernels = []
    for _ in range(depth):
        kernels += [(dim, 3 * hd), (hd, dim),
                    (dim, dim * mult * 2), (dim * mult, dim)]
    kernels.append((dim, total_tokens))           # head / tied table
    w_el = sum(i * o for i, o in kernels)
    w_scale_el = sum(o for _, o in kernels)       # per-output-channel f32

    # mean attended prefix over the image band: bos + text + half the grid
    avg_len = cfg.text_seq_len + 1 + cfg.image_seq_len / 2
    kv_el = depth * 2 * hd * avg_len
    kv_scale_el = depth * 2 * h * avg_len         # per-(h, pos) f32, int8

    w_bytes = {"f32": 4, "bf16": 2, "bf16_int8kv": 2,
               "int8w_int8kv": 1}[mode] * w_el
    if mode == "int8w_int8kv":
        w_bytes += 4 * w_scale_el
    kv_bytes = {"f32": 4, "bf16": 2, "bf16_int8kv": 1,
                "int8w_int8kv": 1}[mode] * kv_el
    if mode in ("bf16_int8kv", "int8w_int8kv"):
        kv_bytes += 4 * kv_scale_el
    return {"weights_mb": round(w_bytes / 2**20, 2),
            "kv_mb": round(kv_bytes / 2**20, 2),
            "total_mb": round((w_bytes + kv_bytes) / 2**20, 2)}


_LEDGER_MODE = {"f32": "f32", "bf16": "bf16", "bf16_int8kv": "bf16_int8kv",
                "int8w_int8kv": "int8w_int8kv",
                "int8kv_fast_topk": "bf16_int8kv"}


def print_ledger(cfg, label: str):
    rows = {}
    base = None
    for mode in ("f32", "bf16", "bf16_int8kv", "int8w_int8kv"):
        led = decode_hbm_bytes_per_token(cfg, mode)
        if base is None:
            base = led["total_mb"]
        led["vs_f32"] = round(base / led["total_mb"], 2)
        rows[mode] = led
        print(f"{mode:>14}: weights {led['weights_mb']:8.2f} MB/tok  "
              f"kv {led['kv_mb']:7.2f} MB/tok  total {led['total_mb']:8.2f} "
              f"MB/tok  ({led['vs_f32']}x less than f32)")
    print(json.dumps({"metric": "decode_hbm_ledger", "config": label,
                      "rows": rows}))
    return rows


def decode_modes(model, params):
    """[(name, decode_params, cache_dtype, topk_approx)] for every decode
    fast path."""
    import jax.numpy as jnp
    from dalle_tpu.ops.quantize_weights import quantize_params_int8
    from dalle_tpu.train.train_state import cast_floating

    bf16 = cast_floating(params, jnp.bfloat16)
    int8w = quantize_params_int8(params)
    return [
        ("f32", params, jnp.float32, False),
        ("bf16", bf16, jnp.bfloat16, False),
        ("bf16_int8kv", bf16, jnp.int8, False),
        ("int8w_int8kv", int8w, jnp.int8, False),
        ("int8kv_fast_topk", bf16, jnp.int8, True),
    ]


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--image_size", type=int, default=32)
    ap.add_argument("--num_tokens", type=int, default=64)
    ap.add_argument("--vae_steps", type=int, default=500)
    ap.add_argument("--dalle_steps", type=int, default=800)
    ap.add_argument("--batch_size", type=int, default=32)
    ap.add_argument("--train_frac", type=float, default=0.3)
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--depth", type=int, default=4)
    ap.add_argument("--eval_n", type=int, default=64,
                    help="captions scored (train split — the notebook's "
                         "token-accuracy bar is the train split)")
    ap.add_argument("--timing_iters", type=int, default=5)
    ap.add_argument("--pad_text_to", type=int, default=None,
                    help="pad text_seq_len up to this (e.g. 64 with "
                         "image_size 32 gives total_seq 128 so the Pallas "
                         "decode kernel engages on TPU)")
    ap.add_argument("--outdir", type=str, default="/tmp/eval_decode_prec")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--small", action="store_true",
                    help="CPU-sized: 16px, fewer steps")
    ap.add_argument("--ledger", action="store_true",
                    help="print the analytic HBM-bytes-per-token ledger "
                         "for the flagship serve config and exit (no "
                         "training — the numbers docs/PERFORMANCE.md "
                         "quotes)")
    args = ap.parse_args(argv)

    if args.ledger:
        from dalle_tpu.config import DalleConfig as _DC
        flagship = _DC(num_text_tokens=49408, text_seq_len=256, dim=1792,
                       depth=24, heads=14, dim_head=128, image_size=128,
                       image_vocab_size=8192, image_fmap_size=16)
        print_ledger(flagship, "flagship-1.4B (24L/14H/1792d, 256+256)")
        return 0
    if args.small:
        args.image_size, args.num_tokens = 16, 32
        args.vae_steps, args.dalle_steps = 300, 500
        args.dim, args.depth, args.eval_n = 64, 2, 32
        args.timing_iters = 2

    import jax
    import jax.numpy as jnp
    import numpy as np
    from dalle_tpu.models.dalle import DALLE

    model, params, text, codes, tr_idx = train_rainbow(args)

    sel = tr_idx[:args.eval_n]
    t = jnp.asarray(text[sel])
    key = jax.random.PRNGKey(1)
    rows = []
    for name, p, cache_dtype, approx in decode_modes(model, params):
        gen = jax.jit(lambda p, t, k, cd=cache_dtype, ap=approx: model.apply(
            p, t, k, filter_thres=0.9, temperature=0.5, cache_dtype=cd,
            topk_approx=ap, method=DALLE.generate_images_tokens))
        ids = np.asarray(gen(p, t, key))          # compile + sample
        acc = float((ids == codes[sel]).mean())
        t0 = time.perf_counter()
        for _ in range(args.timing_iters):
            jax.block_until_ready(gen(p, t, key))
        # the axon tunnel can lie about block_until_ready: hard-sync
        float(jnp.sum(gen(p, t, key)))
        dt_ms = (time.perf_counter() - t0) / (args.timing_iters + 1) * 1e3
        led = decode_hbm_bytes_per_token(model.cfg, _LEDGER_MODE[name])
        rows.append({"mode": name, "token_exact": round(acc, 4),
                     "decode_ms": round(dt_ms, 1),
                     "hbm_mb_per_tok": led["total_mb"]})
        print(f"{name:>14}: token-exact {acc:.4f}  decode {dt_ms:.1f} ms "
              f"(batch {len(sel)})  hbm {led['total_mb']} MB/tok")

    base = rows[0]["token_exact"]
    for r in rows:
        r["delta_vs_f32"] = round(r["token_exact"] - base, 4)
    print(json.dumps({"metric": "decode_precision_referee", "rows": rows,
                      "batch": int(len(sel)),
                      "image_seq_len": int(codes.shape[1])}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
