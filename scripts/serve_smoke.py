#!/usr/bin/env python
"""Continuous-batching serve smoke — the CI gate for dalle_tpu/serve.

A short offered-load run on a tiny model (CPU mesh) asserting the three
serving contracts that must never drift:

  * token-exactness — every completed request's tokens equal single-request
    ``generate_images_tokens(text[None], PRNGKey(seed))`` bitwise, despite
    ragged admission through shared-cache slots;
  * work conservation — slot occupancy stays ≥ 90% at iterations where the
    queue still held requests (continuous batching's whole point), and the
    queue drains (every submitted request completes, FIFO admission order);
  * observability — tracing captures one ``serve/request`` +
    ``serve/request_ttft`` span per request with sane timings, and the
    queue-depth / occupancy gauges + token counters are live.

Artifacts (smoke.json, serve_spans.jsonl) land in ``--outdir`` — the dir
ci.yml uploads. Run: JAX_PLATFORMS=cpu python scripts/serve_smoke.py
"""

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--outdir", type=str, default="serve_artifacts")
    ap.add_argument("--slots", type=int, default=3)
    ap.add_argument("--n_requests", type=int, default=8)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--precision", choices=("int8w", "float32"),
                    default="int8w",
                    help="serving precision under test (default: the "
                         "engine's int8-weights + int8-KV production "
                         "default; references run the same mode, so the "
                         "exactness bar stays bitwise)")
    args = ap.parse_args(argv)
    os.makedirs(args.outdir, exist_ok=True)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from dalle_tpu import obs
    from dalle_tpu.config import DalleConfig
    from dalle_tpu.models.dalle import DALLE, init_dalle
    from dalle_tpu.serve import DecodeEngine, RequestQueue

    cfg = DalleConfig(num_text_tokens=32, text_seq_len=6, dim=64, depth=2,
                      heads=2, dim_head=32, image_size=16,
                      image_vocab_size=24, image_fmap_size=4)
    model, params = init_dalle(cfg, jax.random.PRNGKey(args.seed), batch=2)
    if args.precision == "int8w":
        # the serving default (DalleWithVae.serve_engine): int8 matmul
        # kernels + per-channel scales, everything else bf16, int8 KV
        from dalle_tpu.ops.quantize_weights import quantize_params_int8
        params = quantize_params_int8(params)
        cache_dtype = jnp.int8
    else:
        cache_dtype = jnp.float32
    rng = np.random.RandomState(args.seed)
    texts = [rng.randint(1, 20, (cfg.text_seq_len,)).astype(np.int32)
             for _ in range(args.n_requests)]

    # sequential references, one per request under its own key — same
    # params tree and cache dtype as the engine, so exactness is bitwise
    refs = {}
    for i, t in enumerate(texts):
        ids = model.apply(params, jnp.asarray(t[None]),
                          jax.random.PRNGKey(1000 + i),
                          cache_dtype=cache_dtype,
                          method=DALLE.generate_images_tokens)
        refs[i] = np.asarray(ids[0])

    tracer = obs.configure()
    q = RequestQueue()
    # offered load: a burst up front plus staggered submissions from a
    # producer thread, so admission interleaves with mid-flight decode
    for i in range(args.slots + 1):
        q.submit(texts[i], seed=1000 + i, request_id=i)

    def producer():
        for i in range(args.slots + 1, args.n_requests):
            time.sleep(0.02)
            q.submit(texts[i], seed=1000 + i, request_id=i)
        q.close()

    th = threading.Thread(target=producer)
    th.start()
    eng = DecodeEngine(model, params, slots=args.slots,
                       cache_dtype=cache_dtype)
    t0 = time.perf_counter()
    done = eng.run(q)
    wall = time.perf_counter() - t0
    th.join()

    failures = []

    def check(ok, msg):
        print(("PASS " if ok else "FAIL ") + msg)
        if not ok:
            failures.append(msg)

    check(len(done) == args.n_requests,
          f"drain: {len(done)}/{args.n_requests} requests completed")
    exact = all(bool((c.tokens == refs[c.request_id]).all()) for c in done)
    check(exact, "token-exact vs single-request generation for every "
          "request (any admission order)")
    occ = eng.stats.occupancy_while_queued
    check(occ >= 0.90, f"slot occupancy while queue nonempty: {occ:.3f} "
          ">= 0.90")
    check(all(c.first_token_at >= c.admitted_at >= c.submitted_at
              and c.completed_at >= c.first_token_at for c in done),
          "per-request timestamps are ordered "
          "(submit <= admit <= first token <= complete)")

    spans = tracer.snapshot_spans()
    by_name = {}
    for name, rel, dur, tid, depth, sargs in spans:
        by_name.setdefault(name, []).append((dur, sargs))
    for want in ("serve/request", "serve/request_ttft"):
        rows = by_name.get(want, [])
        ids = sorted(a["request_id"] for _, a in rows)
        check(ids == list(range(args.n_requests)),
              f"{want}: one span per request with request_id args")
        check(all(0 <= d <= wall + 1 for d, _ in rows),
              f"{want}: durations within the run wall clock")
    metrics = obs.metrics_snapshot()
    check(metrics.get("serve.requests_completed_total") == len(done),
          "serve.requests_completed_total counter matches completions")
    check(metrics.get("serve.tokens_emitted_total", 0)
          >= args.n_requests * cfg.image_seq_len,
          "serve.tokens_emitted_total covers every request's tokens")

    n_spans = obs.export_spans_jsonl(
        os.path.join(args.outdir, "serve_spans.jsonl"))
    summary = {
        "requests": args.n_requests, "slots": args.slots,
        "precision": args.precision,
        "wall_s": round(wall, 3), "steps": eng.stats.steps,
        "refills": eng.stats.refills,
        "occupancy_while_queued": round(occ, 4),
        "token_exact": exact, "spans_exported": n_spans,
        "completed_per_s": round(len(done) / wall, 3),
        "p50_latency_s": round(float(np.median(
            [c.latency_s for c in done])), 4) if done else None,
        "failures": failures,
    }
    with open(os.path.join(args.outdir, "smoke.json"), "w") as fh:
        json.dump(summary, fh, indent=2)
    obs.disable()
    print(json.dumps({"metric": "serve_smoke", **summary}), flush=True)
    if failures:
        print(f"serve_smoke: FAILED ({len(failures)} checks)")
        return 1
    print("serve_smoke: GREEN")
    return 0


if __name__ == "__main__":
    sys.exit(main())
