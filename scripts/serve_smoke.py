#!/usr/bin/env python
"""Continuous-batching serve smoke — the CI gate for dalle_tpu/serve.

A short offered-load run on a tiny model (CPU mesh) asserting the three
serving contracts that must never drift:

  * token-exactness — every completed request's tokens equal single-request
    ``generate_images_tokens(text[None], PRNGKey(seed))`` bitwise, despite
    ragged admission through shared-cache slots;
  * work conservation — slot occupancy stays ≥ 90% at iterations where the
    queue still held requests (continuous batching's whole point), and the
    queue drains (every submitted request completes, FIFO admission order);
  * observability — tracing captures one ``serve/request`` +
    ``serve/request_ttft`` span per request with sane timings, and the
    queue-depth / occupancy gauges + token counters are live.

A second phase reruns the workload through the PAGED engine (graftpage,
``kv_block_tokens=4``): exactness must survive block remaps, radix prefix
hits and COW forks, repeated prompts must actually hit the radix cache,
and — after one warmup run — a fresh admission mix must trigger ZERO XLA
compiles (the page table is device data, never program shape).

Artifacts (smoke.json, serve_spans.jsonl) land in ``--outdir`` — the dir
ci.yml uploads. Run: JAX_PLATFORMS=cpu python scripts/serve_smoke.py
"""

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--outdir", type=str, default="serve_artifacts")
    ap.add_argument("--slots", type=int, default=3)
    ap.add_argument("--n_requests", type=int, default=8)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--precision", choices=("int8w", "float32"),
                    default="int8w",
                    help="serving precision under test (default: the "
                         "engine's int8-weights + int8-KV production "
                         "default; references run the same mode, so the "
                         "exactness bar stays bitwise)")
    args = ap.parse_args(argv)
    os.makedirs(args.outdir, exist_ok=True)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from dalle_tpu import obs
    from dalle_tpu.config import DalleConfig
    from dalle_tpu.models.dalle import DALLE, init_dalle
    from dalle_tpu.serve import DecodeEngine, RequestQueue

    cfg = DalleConfig(num_text_tokens=32, text_seq_len=6, dim=64, depth=2,
                      heads=2, dim_head=32, image_size=16,
                      image_vocab_size=24, image_fmap_size=4)
    model, params = init_dalle(cfg, jax.random.PRNGKey(args.seed), batch=2)
    if args.precision == "int8w":
        # the serving default (DalleWithVae.serve_engine): int8 matmul
        # kernels + per-channel scales, everything else bf16, int8 KV
        from dalle_tpu.ops.quantize_weights import quantize_params_int8
        params = quantize_params_int8(params)
        cache_dtype = jnp.int8
    else:
        cache_dtype = jnp.float32
    rng = np.random.RandomState(args.seed)
    texts = [rng.randint(1, 20, (cfg.text_seq_len,)).astype(np.int32)
             for _ in range(args.n_requests)]

    # sequential references, one per request under its own key — same
    # params tree and cache dtype as the engine, so exactness is bitwise
    refs = {}
    for i, t in enumerate(texts):
        ids = model.apply(params, jnp.asarray(t[None]),
                          jax.random.PRNGKey(1000 + i),
                          cache_dtype=cache_dtype,
                          method=DALLE.generate_images_tokens)
        refs[i] = np.asarray(ids[0])

    tracer = obs.configure()
    q = RequestQueue()
    # offered load: a burst up front plus staggered submissions from a
    # producer thread, so admission interleaves with mid-flight decode
    for i in range(args.slots + 1):
        q.submit(texts[i], seed=1000 + i, request_id=i)

    def producer():
        for i in range(args.slots + 1, args.n_requests):
            time.sleep(0.02)
            q.submit(texts[i], seed=1000 + i, request_id=i)
        q.close()

    th = threading.Thread(target=producer)
    th.start()
    eng = DecodeEngine(model, params, slots=args.slots,
                       cache_dtype=cache_dtype)
    t0 = time.perf_counter()
    done = eng.run(q)
    wall = time.perf_counter() - t0
    th.join()

    failures = []

    def check(ok, msg):
        print(("PASS " if ok else "FAIL ") + msg)
        if not ok:
            failures.append(msg)

    check(len(done) == args.n_requests,
          f"drain: {len(done)}/{args.n_requests} requests completed")
    exact = all(bool((c.tokens == refs[c.request_id]).all()) for c in done)
    check(exact, "token-exact vs single-request generation for every "
          "request (any admission order)")
    occ = eng.stats.occupancy_while_queued
    check(occ >= 0.90, f"slot occupancy while queue nonempty: {occ:.3f} "
          ">= 0.90")
    check(all(c.first_token_at >= c.admitted_at >= c.submitted_at
              and c.completed_at >= c.first_token_at for c in done),
          "per-request timestamps are ordered "
          "(submit <= admit <= first token <= complete)")

    spans = tracer.snapshot_spans()
    by_name = {}
    for name, rel, dur, tid, depth, sargs in spans:
        by_name.setdefault(name, []).append((dur, sargs))
    for want in ("serve/request", "serve/request_ttft"):
        rows = by_name.get(want, [])
        ids = sorted(a["request_id"] for _, a in rows)
        check(ids == list(range(args.n_requests)),
              f"{want}: one span per request with request_id args")
        check(all(0 <= d <= wall + 1 for d, _ in rows),
              f"{want}: durations within the run wall clock")
    metrics = obs.metrics_snapshot()
    check(metrics.get("serve.requests_completed_total") == len(done),
          "serve.requests_completed_total counter matches completions")
    check(metrics.get("serve.tokens_emitted_total", 0)
          >= args.n_requests * cfg.image_seq_len,
          "serve.tokens_emitted_total covers every request's tokens")

    # ----- phase 2: paged KV (graftpage) ---------------------------------
    # the same workload through the paged engine: tokens must stay bitwise
    # the sequential references through block remaps, radix prefix hits and
    # COW forks — and once one warmup run has compiled the fixed program
    # set, a fresh run with a DIFFERENT admission mix (staggered arrivals,
    # repeated prompts, pool churn) must compile NOTHING. That is the
    # no-recompile invariant: the page table is data, never shape.
    counter = obs.install_compile_counter()
    # pool sized for live rows PLUS radix residency: the default (slots ×
    # blocks/slot) keeps HBM parity with the dense slab but leaves zero
    # headroom for cached prefixes, so every resident would be evicted
    # before its repeat arrives — the smoke wants hits to be demonstrable
    bt = 4
    blocks_per_slot = -(-cfg.total_seq_len // bt)
    peng = DecodeEngine(model, params, slots=args.slots,
                        cache_dtype=cache_dtype, kv_block_tokens=bt,
                        kv_pool_blocks=(args.slots + args.n_requests)
                        * blocks_per_slot)
    # warmup must touch EVERY program in the fixed set: a burst (bulk
    # refill + step scan), a trickled fresh prompt (the block-width prefill
    # chunks), and a trickled repeat (radix hit -> COW fork + the width-1
    # recompute chunk)
    warm = {2: (4, 3000), 3: (0, 3001)}        # id -> (text idx, seed)
    warm_refs = {}
    for rid, (src, seed) in warm.items():
        ids = model.apply(params, jnp.asarray(texts[src][None]),
                          jax.random.PRNGKey(seed), cache_dtype=cache_dtype,
                          method=DALLE.generate_images_tokens)
        warm_refs[rid] = np.asarray(ids[0])
    wq = RequestQueue()
    for i in range(2):
        wq.submit(texts[i], seed=1000 + i, request_id=i)

    def warm_producer():
        for rid, (src, seed) in warm.items():
            time.sleep(0.05)
            wq.submit(texts[src], seed=seed, request_id=rid)
        wq.close()

    wth = threading.Thread(target=warm_producer)
    wth.start()
    wdone = peng.run(wq)
    wth.join()
    check(all(bool((c.tokens == (warm_refs[c.request_id]
                                 if c.request_id in warm_refs
                                 else refs[c.request_id])).all())
              for c in wdone),
          "paged warmup: token-exact vs the sequential references")
    warm_hit_tok = peng.stats.prefix_hit_tokens   # stats reset per run()
    # repeat prompts ride NEW seeds — a radix hit shares prompt KV between
    # requests whose decodes then diverge; references are sequential and
    # fully independent, computed BEFORE the zero-compile window opens
    dup_refs = {}
    for j, src in enumerate((2, 3)):
        rid, seed = args.n_requests + j, 4000 + j
        ids = model.apply(params, jnp.asarray(texts[src][None]),
                          jax.random.PRNGKey(seed), cache_dtype=cache_dtype,
                          method=DALLE.generate_images_tokens)
        dup_refs[rid] = (src, np.asarray(ids[0]), seed)
    compiles_before = counter.count
    q2 = RequestQueue()
    for i in range(2, args.slots + 3):
        q2.submit(texts[i], seed=1000 + i, request_id=i)

    def paged_producer():
        for i in range(args.slots + 3, args.n_requests):
            time.sleep(0.02)
            q2.submit(texts[i], seed=1000 + i, request_id=i)
        for rid, (src, _, seed) in dup_refs.items():
            time.sleep(0.02)
            q2.submit(texts[src], seed=seed, request_id=rid)
        q2.close()

    th2 = threading.Thread(target=paged_producer)
    th2.start()
    pdone = peng.run(q2)
    th2.join()
    paged_compiles = counter.count - compiles_before
    check(len(pdone) == args.n_requests,
          f"paged drain: {len(pdone)}/{args.n_requests} requests completed")
    pexact = all(bool((c.tokens == (dup_refs[c.request_id][1]
                                    if c.request_id in dup_refs
                                    else refs[c.request_id])).all())
                 for c in pdone)
    check(pexact, "paged: token-exact vs sequential references (radix "
          "hits and COW forks included)")
    check(peng.stats.radix_full_hits >= 2,
          f"paged: repeated prompts hit the radix cache "
          f"({peng.stats.radix_full_hits} full hits)")
    check(paged_compiles == 0,
          f"paged no-recompile invariant: {paged_compiles} XLA compiles "
          "after warmup (page-table updates are data, not shape)")
    kv = peng.kv_stats()
    m2 = obs.metrics_snapshot()
    # the counter is cumulative across serve loops; the radix ledger and
    # EngineStats reset per run — the warmup run's hits are part of the
    # counter's total
    check(m2.get("kv.prefix_hit_tokens_total", 0)
          == warm_hit_tok + kv["prefix_hit_tokens"]
          and kv["prefix_hit_tokens"] > 0,
          "kv.prefix_hit_tokens_total counter matches the radix ledger")

    n_spans = obs.export_spans_jsonl(
        os.path.join(args.outdir, "serve_spans.jsonl"))
    summary = {
        "requests": args.n_requests, "slots": args.slots,
        "precision": args.precision,
        "wall_s": round(wall, 3), "steps": eng.stats.steps,
        "refills": eng.stats.refills,
        "occupancy_while_queued": round(occ, 4),
        "token_exact": exact, "spans_exported": n_spans,
        "paged": {"token_exact": pexact, "compiles_after_warmup":
                  paged_compiles, "radix_full_hits":
                  peng.stats.radix_full_hits, "prefix_hit_tokens":
                  kv["prefix_hit_tokens"], "cow_copies": kv["cow_copies"],
                  "pages_evicted": peng.stats.pages_evicted},
        "completed_per_s": round(len(done) / wall, 3),
        "p50_latency_s": round(float(np.median(
            [c.latency_s for c in done])), 4) if done else None,
        "failures": failures,
    }
    with open(os.path.join(args.outdir, "smoke.json"), "w") as fh:
        json.dump(summary, fh, indent=2)
    obs.disable()
    print(json.dumps({"metric": "serve_smoke", **summary}), flush=True)
    if failures:
        print(f"serve_smoke: FAILED ({len(failures)} checks)")
        return 1
    print("serve_smoke: GREEN")
    return 0


if __name__ == "__main__":
    sys.exit(main())
