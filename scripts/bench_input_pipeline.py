#!/usr/bin/env python
"""Input-pipeline throughput: can one host core feed one chip? (VERDICT r4 #3)

Measures the host-side data path the 1.4B trainer consumes — tar-shard
streaming → JPEG decode+resize → BPE tokenize → batch, with and without
decode workers / prefetch — in imgs/s per host core, against the flagship's
measured consumption rate (BENCH: ~13.6k tok/s/chip ÷ 513 tok/sample ≈ 26.6
samples/s/chip). Prints one JSON line per stage and a summary line.

Reference bar: the wds chain this replaces (legacy/train_dalle.py:365-423 —
a naive PIL loop the SURVEY §7 hard-parts list flags as unable to feed a
pod).

Synthetic shards: 256×256 JPEGs (web-scrape scale) + caption txt, written
with data/webdataset.write_shards. No network, no torch.
"""

import io
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def build_shards(root: str, n_samples: int = 2048,
                 samples_per_shard: int = 512, src_px: int = 256):
    """Deterministic JPEG+txt shards; returns the shard paths."""
    from PIL import Image

    from dalle_tpu.data.webdataset import write_shards

    rng = np.random.RandomState(0)
    words = ("red green blue small large circle square star over under a the"
             .split())

    def samples():
        for i in range(n_samples):
            # structured noise compresses like a photo, not like white noise
            base = rng.randint(0, 255, (8, 8, 3), np.uint8)
            img = Image.fromarray(base).resize((src_px, src_px),
                                               Image.BILINEAR)
            buf = io.BytesIO()
            img.save(buf, "JPEG", quality=90)
            cap = " ".join(rng.choice(words, 12))
            yield {"__key__": f"{i:06d}", "jpg": buf.getvalue(), "txt": cap}

    os.makedirs(root, exist_ok=True)
    return write_shards(samples(), os.path.join(root, "shard-{:04d}.tar"),
                        samples_per_shard)


def timed(name, iterator, n_samples, batch_size=1, extra=None):
    t0 = time.perf_counter()
    seen = 0
    for item in iterator:
        seen += batch_size
        if seen >= n_samples:
            break
    dt = time.perf_counter() - t0
    rate = seen / dt
    line = {"stage": name, "samples": seen, "secs": round(dt, 2),
            "imgs_per_s": round(rate, 1)}
    if extra:
        line.update(extra)
    print(json.dumps(line), flush=True)
    return rate


def main():
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--root", default="/tmp/wds_bench")
    ap.add_argument("--n", type=int, default=2048)
    ap.add_argument("--image_size", type=int, default=128)
    ap.add_argument("--consumption_tok_s", type=float, default=13622.0,
                    help="flagship chip consumption (BENCH_r04)")
    ap.add_argument("--seq_len", type=int, default=513)
    args = ap.parse_args()

    from dalle_tpu.data.webdataset import (WebDataset, iter_tar_samples,
                                           reraise)
    from dalle_tpu.text.tokenizer import get_tokenizer

    marker = os.path.join(args.root, f"ready_{args.n}")
    if not os.path.exists(marker):
        t0 = time.perf_counter()
        build_shards(args.root, args.n)
        open(marker, "w").write("ok")
        print(json.dumps({"stage": "build_shards", "samples": args.n,
                          "secs": round(time.perf_counter() - t0, 2)}),
              flush=True)

    shards = sorted(
        os.path.join(args.root, f) for f in os.listdir(args.root)
        if f.endswith(".tar"))

    # 1. raw tar streaming (no decode)
    def raw():
        for s in shards:
            yield from iter_tar_samples(s, reraise)
    timed("tar_stream", raw(), args.n)

    # 2. + JPEG decode + resize, single-threaded
    r_dec = timed(
        "decode_1thread",
        iter(WebDataset(shards, handler=reraise)
             .decode(image_size=args.image_size)),
        args.n)

    # 3. + decode on 4 worker threads (PIL releases the GIL in codecs —
    #    on a 1-core box this mostly measures that the overlap machinery
    #    doesn't cost; on a real multi-core host it scales)
    r_dec4 = timed(
        "decode_4workers",
        iter(WebDataset(shards, handler=reraise)
             .decode(image_size=args.image_size, workers=4)),
        args.n)

    # 4. BPE tokenization alone (batch of captions per call, the trainer's
    #    encode_batch shape)
    tok = get_tokenizer("simple")
    caps = [" ".join(["a red circle over the blue square"] * 2)] * 256
    t0 = time.perf_counter()
    reps = 40
    for _ in range(reps):
        tok.tokenize(caps, 256, truncate_text=True)
    bpe_rate = reps * len(caps) / (time.perf_counter() - t0)
    print(json.dumps({"stage": "bpe_tokenize", "caps_per_s":
                      round(bpe_rate, 1)}), flush=True)

    # 5. full chain exactly as scripts/train_dalle.py builds it: decode →
    #    to pair → shuffle → batch → prefetch thread → tokenize per batch
    bsz = 64
    wds = (WebDataset(shards, handler=reraise, shuffle_shards=True,
                      repeat=True)
           .decode(image_size=args.image_size, workers=4)
           .map(lambda s: (s["jpg"], s["txt"]))
           .shuffle(256)
           .batched(bsz))

    def full():
        for imgs, capss in wds.prefetch():
            text = tok.tokenize(list(capss), 256, truncate_text=True)
            yield np.stack(imgs), text
    r_full = timed("full_pipeline_b64", full(), args.n, batch_size=bsz)

    need = args.consumption_tok_s / args.seq_len
    print(json.dumps({
        "metric": "input_pipeline_imgs_per_s_per_core",
        "value": round(r_full, 1), "unit": "imgs/s/core",
        "chip_consumption_imgs_per_s": round(need, 1),
        "margin_x": round(r_full / need, 2),
        "decode_1t": round(r_dec, 1), "decode_4w": round(r_dec4, 1),
        "bpe_caps_per_s": round(bpe_rate, 1),
    }), flush=True)


if __name__ == "__main__":
    main()
