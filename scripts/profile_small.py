#!/usr/bin/env python
"""Decompose the DALL·E-small train step on the real chip: which component
owns the gap between the ~80ms flops-ideal and the ~194ms measured step?

Methodology: scripts/_bench_util.timed_scan — every candidate runs K times
in one dispatched scan; all floating inputs (INCLUDING weights, passed as
arguments, never closures) are carry-perturbed so nothing hoists, and
"fwd+bwd" rows take gradients wrt every floating input so no backward
matmul is dead-code-eliminated.

Usage: python scripts/profile_small.py [K]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np

from _bench_util import timed_scan


def main():
    k = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    from dalle_tpu.config import DalleConfig
    from dalle_tpu.models.dalle import init_dalle
    from dalle_tpu.train.train_state import cast_floating

    cfg = DalleConfig(
        num_text_tokens=10000, text_seq_len=256, dim=512, depth=12, heads=8,
        dim_head=64, image_size=128, image_vocab_size=8192,
        image_fmap_size=16, attn_softmax_f32=False)
    b, n, d = 64, cfg.total_seq_len, cfg.dim
    model, params = init_dalle(cfg, jax.random.PRNGKey(0))
    bf16 = cast_floating(params, jnp.bfloat16)
    rng = np.random.RandomState(0)
    text = jnp.asarray(rng.randint(1, cfg.num_text_tokens,
                                   (b, cfg.text_seq_len)), jnp.int32)
    ids = jnp.asarray(rng.randint(0, cfg.image_vocab_size,
                                  (b, cfg.image_seq_len)), jnp.int32)

    report = {}

    # 1. full loss: params are a perturbed ARGUMENT (closure would hoist)
    def loss(p, text, ids):
        l, _ = model.apply(p, text, ids, return_loss=True)
        return l

    report["loss_fwd"] = timed_scan(loss, (bf16, text, ids), k)
    report["loss_fwd_bwd"] = timed_scan(loss, (bf16, text, ids), k,
                                        grad=True, grad_argnums=(0,))

    # 2. transformer stack alone (params + activations both differentiated)
    from dalle_tpu.models.transformer import Transformer
    tr = Transformer(cfg.transformer())
    x = jnp.asarray(rng.standard_normal((b, n, d)), jnp.bfloat16)
    tp = cast_floating(tr.init(jax.random.PRNGKey(1), x), jnp.bfloat16)
    report["transformer_fwd"] = timed_scan(
        lambda p, x: tr.apply(p, x), (tp, x), k)
    report["transformer_fwd_bwd"] = timed_scan(
        lambda p, x: tr.apply(p, x), (tp, x), k, grad=True)

    # 3. vocab head + CE alone (grads wrt x and W — the real training work)
    V = cfg.total_tokens
    W = jnp.asarray(rng.standard_normal((d, V)) * 0.02, jnp.bfloat16)
    labels = jnp.asarray(rng.randint(0, V, (b, n)), jnp.int32)

    def head_ce(x, W):
        logits = (x @ W).astype(jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, labels[..., None], -1)[..., 0]
        return jnp.mean(lse - gold)

    report["head_ce_fwd"] = timed_scan(head_ce, (x, W), k)
    report["head_ce_fwd_bwd"] = timed_scan(head_ce, (x, W), k, grad=True)

    # 4. attention cores only: 12x attend (no projections; q=k=v inputs all
    # differentiated — dk/dv matmuls stay live)
    from dalle_tpu.ops.attention import attend
    q = jnp.asarray(rng.standard_normal((b, cfg.heads, n, cfg.dim_head)),
                    jnp.bfloat16)

    def attn12(q, kk, vv):
        y = q
        for _ in range(cfg.depth):
            y = attend(y, kk, vv, causal=True, softmax_f32=False)
        return y

    report["attend_x12_fwd"] = timed_scan(attn12, (q, q, q), k)
    report["attend_x12_fwd_bwd"] = timed_scan(attn12, (q, q, q), k, grad=True)

    # 5. FF stack reference: weights are differentiated arguments, so the
    # backward includes dW1/dW2 like real training
    W1 = jnp.asarray(rng.standard_normal((d, 4 * d)) * 0.02, jnp.bfloat16)
    W2 = jnp.asarray(rng.standard_normal((4 * d, d)) * 0.02, jnp.bfloat16)

    def ff12(x, W1, W2):
        y = x
        for _ in range(cfg.depth):
            y = jax.nn.gelu(y @ W1) @ W2
        return y

    report["ff_x12_fwd"] = timed_scan(ff12, (x, W1, W2), k)
    report["ff_x12_fwd_bwd"] = timed_scan(ff12, (x, W1, W2), k, grad=True)

    for name, dt in report.items():
        print(f"{name:24s} {dt * 1e3:8.2f} ms")


if __name__ == "__main__":
    main()
