#!/usr/bin/env python
"""Decompose the DALL·E-small train step on the real chip: which component
owns the gap between the ~60ms flops-ideal and the ~195ms measured step?

Each candidate subprogram runs K times inside ONE dispatched lax.scan (the
input is perturbed by the carry so XLA cannot hoist the body), so per-call
tunnel overhead (~20ms here) is excluded from every number.

Usage: python scripts/profile_small.py [K]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def timed_scan(fn, args, k=8, grad=False, wrt=0):
    """Time fn (or grad of fn) executed k times inside one scan dispatch.
    Returns seconds per execution."""
    if grad:
        base = jax.grad(
            lambda *a: jnp.sum(fn(*a).astype(jnp.float32) ** 2), argnums=wrt)
    else:
        base = fn

    @jax.jit
    def many(args):
        def body(c, _):
            perturbed = tuple(
                a + jnp.asarray(1e-12 * c, a.dtype)
                if jnp.issubdtype(a.dtype, jnp.floating) else a
                for a in args)
            out = base(*perturbed)
            s = (jnp.sum(out[0] if isinstance(out, tuple) else out)
                 .astype(jnp.float32))
            return c + s * 0e0 + 1e-30 * s, None

        c, _ = jax.lax.scan(body, jnp.float32(0.0), None, length=k)
        return c

    r = many(args)
    float(jax.device_get(r))           # warm/compile + hard sync
    t0 = time.perf_counter()
    r = many(args)
    float(jax.device_get(r))
    return (time.perf_counter() - t0) / k


def main():
    k = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    from dalle_tpu.config import DalleConfig
    from dalle_tpu.models.dalle import init_dalle
    from dalle_tpu.train.train_state import cast_floating

    cfg = DalleConfig(
        num_text_tokens=10000, text_seq_len=256, dim=512, depth=12, heads=8,
        dim_head=64, image_size=128, image_vocab_size=8192,
        image_fmap_size=16, attn_softmax_f32=False)
    b, n, d = 64, cfg.total_seq_len, cfg.dim
    model, params = init_dalle(cfg, jax.random.PRNGKey(0))
    bf16 = cast_floating(params, jnp.bfloat16)
    rng = np.random.RandomState(0)
    text = jnp.asarray(rng.randint(1, cfg.num_text_tokens,
                                   (b, cfg.text_seq_len)), jnp.int32)
    ids = jnp.asarray(rng.randint(0, cfg.image_vocab_size,
                                  (b, cfg.image_seq_len)), jnp.int32)

    report = {}

    # 1. full loss fwd (bf16 params like the train step)
    def loss(p, text, ids):
        l, _ = model.apply(p, text, ids, return_loss=True)
        return l

    report["loss_fwd"] = timed_scan(
        lambda t, i: loss(bf16, t, i), (text, ids), k)

    # 2. full loss fwd+bwd (grad wrt params — the train step's core)
    gfn = jax.grad(lambda p, t, i: loss(p, t, i))

    @jax.jit
    def many_grad(p, t, i):
        def body(c, _):
            g = gfn(jax.tree.map(
                lambda x: x + jnp.asarray(1e-12 * c, x.dtype)
                if jnp.issubdtype(x.dtype, jnp.floating) else x, p), t, i)
            return c + 1e-30 * jnp.sum(
                jax.tree.leaves(g)[0].astype(jnp.float32)), None
        c, _ = jax.lax.scan(body, jnp.float32(0.0), None, length=k)
        return c

    r = many_grad(bf16, text, ids)
    float(jax.device_get(r))
    t0 = time.perf_counter()
    float(jax.device_get(many_grad(bf16, text, ids)))
    report["loss_fwd_bwd"] = (time.perf_counter() - t0) / k

    # 3. transformer stack alone (fwd and fwd+bwd) on (b, n, d) bf16
    from dalle_tpu.models.transformer import Transformer
    tcfg = cfg.transformer()
    tr = Transformer(tcfg)
    x = jnp.asarray(rng.standard_normal((b, n, d)), jnp.bfloat16)
    tp = tr.init(jax.random.PRNGKey(1), x)
    tpb = cast_floating(tp, jnp.bfloat16)
    report["transformer_fwd"] = timed_scan(
        lambda x: tr.apply(tpb, x), (x,), k)
    report["transformer_fwd_bwd"] = timed_scan(
        lambda x: tr.apply(tpb, x), (x,), k, grad=True)

    # 4. vocab head + CE alone: x(b,n,d) @ W(d, V) + softmax CE fwd+bwd
    V = cfg.total_tokens
    W = jnp.asarray(rng.standard_normal((d, V)) * 0.02, jnp.bfloat16)
    labels = jnp.asarray(rng.randint(0, V, (b, n)), jnp.int32)

    def head_ce(x, W):
        logits = (x @ W).astype(jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, labels[..., None], -1)[..., 0]
        return jnp.mean(lse - gold)

    report["head_ce_fwd"] = timed_scan(head_ce, (x, W), k)
    report["head_ce_fwd_bwd"] = timed_scan(head_ce, (x, W), k, grad=True)

    # 5. attention cores only: 12x attend(b,h,n,dh) (no proj)
    from dalle_tpu.ops.attention import attend
    q = jnp.asarray(rng.standard_normal((b, cfg.heads, n, cfg.dim_head)),
                    jnp.bfloat16)

    def attn12(q):
        y = q
        for _ in range(cfg.depth):
            y = attend(y, q, q, causal=True, softmax_f32=False)
        return y

    report["attend_x12_fwd"] = timed_scan(attn12, (q,), k)
    report["attend_x12_fwd_bwd"] = timed_scan(attn12, (q,), k, grad=True)

    # 6. dense matmul stack reference: 12 layers x (qkv+out+ff) GEMM flops
    W1 = jnp.asarray(rng.standard_normal((d, 4 * d)) * 0.02, jnp.bfloat16)
    W2 = jnp.asarray(rng.standard_normal((4 * d, d)) * 0.02, jnp.bfloat16)

    def ff12(x):
        y = x
        for _ in range(cfg.depth):
            y = jax.nn.gelu(y @ W1) @ W2
        return y

    report["ff_x12_fwd"] = timed_scan(ff12, (x,), k)
    report["ff_x12_fwd_bwd"] = timed_scan(ff12, (x,), k, grad=True)

    for name, dt in report.items():
        print(f"{name:24s} {dt * 1e3:8.2f} ms")


if __name__ == "__main__":
    main()
