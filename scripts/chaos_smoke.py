#!/usr/bin/env python
"""graftmend chaos smoke: scripted fault scenarios over the REAL 2-process
gloo/DCN path, each asserting the recovery invariant (docs/RESILIENCE.md —
the CI stage behind it):

  **bit-exact resume** — post-recovery (params, opt_state) must be
  BITWISE-identical (sha256 over every leaf's raw bytes) to an
  uninterrupted run at the same step.

Scenario catalog (fast set; ``--heavy`` adds the hang-detection scenario,
whose liveness timeouts dominate its runtime):

  * ``kill_respawn`` — SIGKILL worker 1 mid-step; the elastic agent tears
    the epoch down and respawns the full gang; both workers restore the
    last durable step over the real coordinator and resume. Digest must
    equal the clean 2-process reference.
  * ``kill_sigterm`` — SIGTERM instead: the victim finishes its in-flight
    step, takes a synchronous drained save (the graceful-preemption
    contract), and exits asking for reconfiguration; the step it was
    killed at must exist as a durable checkpoint.
  * ``coordinator_flaky`` — the victim's first two
    ``jax.distributed.initialize`` dials fail (injected); the retry layer
    must absorb them (visible as ``retry.attempts_total{op=
    "coordinator_connect"}``), with NO reconfiguration and a clean digest.
  * ``ckpt_io_flaky`` — same for checkpoint-save I/O.
  * ``corrupt_recover`` — corrupt the newest durable checkpoint, then
    SIGKILL: recovery must fall back to the previous durable step
    (``ckpt.restore_fallback_total``), quarantine the corrupt one, and
    still converge to the reference digest.
  * ``shrink`` — SIGKILL under ``policy=shrink``: the pod reshapes to
    world size 1, restores WITH RESHARDING onto the smaller mesh, and
    resumes. Invariant: recovery ≡ a clean single-process run pinned to
    the same restore step (crossing world sizes changes reduction
    grouping, so the oracle holds topology fixed — see RESILIENCE.md).
  * ``hang_detect`` (``--heavy``) — worker 1 hangs mid-step: the
    survivor's peer-liveness watcher and the agent's heartbeat timeout
    must detect it (no exit code to key on), kill it, and recover.

Per-scenario verdicts + the agent event log + a flight-recorder bundle
land in ``--outdir`` (``chaos_artifacts/`` in CI; ci.yml uploads them).

Run: JAX_PLATFORMS=cpu python scripts/chaos_smoke.py --outdir chaos_artifacts
"""

import argparse
import glob
import json
import os
import shutil
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from dalle_tpu.chaos import (EPOCH_ENV, PLAN_ENV, RANK_ENV, Fault,  # noqa: E402
                             FaultPlan)
from dalle_tpu.degrade import DegradeMonitor, StragglerDetector  # noqa: E402
from dalle_tpu.obs import configure as obs_configure  # noqa: E402
from dalle_tpu.obs import configure_recorder, dump_recorder  # noqa: E402
from dalle_tpu.obs import metrics_snapshot  # noqa: E402
from dalle_tpu.parallel.elastic import (DIR_ENV, WORKER_ENV,  # noqa: E402
                                        ElasticAgent, python_worker_env)

WORKER = os.path.join(ROOT, "scripts", "chaos_worker.py")

FAILURES = []


def check(ok: bool, what: str):
    print(("ok   " if ok else "FAIL ") + what)
    if not ok:
        FAILURES.append(what)
    return ok


def child_env(extra=None):
    return python_worker_env(devices_per_proc=1, repo_root=ROOT, extra=extra)


def make_spawn(run_dir: str, cache: str, target: int, save_every: int,
               plan: FaultPlan = None, peer_timeout_s: float = 0.0,
               extra_args: tuple = ()):
    """The ElasticAgent spawn fn: one chaos_worker.py child per member,
    logs to <run_dir>/logs/."""
    logdir = os.path.join(run_dir, "logs")
    os.makedirs(logdir, exist_ok=True)

    def spawn(worker_id, epoch):
        extra = {DIR_ENV: run_dir, WORKER_ENV: str(worker_id)}
        if plan is not None:
            extra.update({PLAN_ENV: plan.to_json(),
                          RANK_ENV: str(worker_id),
                          EPOCH_ENV: str(epoch.epoch)})
        cmd = [sys.executable, WORKER, "--run_dir", run_dir,
               "--target_steps", str(target),
               "--save_every", str(save_every),
               "--compile_cache", cache, *extra_args]
        if peer_timeout_s > 0:
            cmd += ["--peer_timeout_s", str(peer_timeout_s)]
        log = open(os.path.join(
            logdir, f"w{worker_id}_e{epoch.epoch}.log"), "a")
        return subprocess.Popen(cmd, env=child_env(extra), stdout=log,
                                stderr=subprocess.STDOUT, cwd=ROOT)
    return spawn


def read_digests(run_dir: str) -> dict:
    out = {}
    for p in glob.glob(os.path.join(run_dir, "digest_*.json")):
        with open(p, encoding="utf-8") as fh:
            doc = json.load(fh)
        out[os.path.basename(p)[len("digest_"):-len(".json")]] = doc
    return out


def counters_of(digests: dict) -> dict:
    merged = {}
    for doc in digests.values():
        for k, v in doc.get("counters", {}).items():
            merged[k] = merged.get(k, 0) + v
    return merged


def tail_logs(run_dir: str, n: int = 30) -> str:
    out = []
    for p in sorted(glob.glob(os.path.join(run_dir, "logs", "*.log"))):
        with open(p, errors="replace") as fh:
            lines = fh.readlines()
        out.append(f"---- {os.path.basename(p)} ----\n"
                   + "".join(lines[-n:]))
    return "\n".join(out)


def run_pod(name: str, outdir: str, cache: str, *, nproc: int, target: int,
            save_every: int, plan: FaultPlan = None, policy: str = "respawn",
            hb_timeout_s: float = 0.0, peer_timeout_s: float = 0.0,
            term_grace_s: float = 5.0, deadline_s: float = 420.0,
            degrade: DegradeMonitor = None, extra_args: tuple = ()):
    """One pod run under the elastic agent; returns (agent, digests)."""
    run_dir = os.path.join(outdir, name)
    shutil.rmtree(run_dir, ignore_errors=True)
    os.makedirs(run_dir)
    agent = ElasticAgent(
        run_dir, make_spawn(run_dir, cache, target, save_every, plan,
                            peer_timeout_s, extra_args),
        members=list(range(nproc)), policy=policy, degrade=degrade,
        hb_timeout_s=hb_timeout_s, term_grace_s=term_grace_s, poll_s=0.2)
    t0 = time.time()
    try:
        agent.run(deadline_s=deadline_s)
    except Exception as exc:  # noqa: BLE001 - a failed pod must produce a
        # verdict + logs, not a stack trace that hides them
        check(False, f"{name}: pod run failed: {exc!r}")
        print(tail_logs(run_dir))
    digests = read_digests(run_dir)
    print(f"-- {name}: {time.time() - t0:.1f}s, "
          f"{agent.reconfigures} reconfigure(s), "
          f"{len(digests)} digest artifact(s)")
    return agent, digests


def verdict(outdir: str, name: str, agent, digests: dict, checks: dict):
    doc = {"scenario": name, "ok": all(checks.values()), "checks": checks,
           "events": agent.events if agent is not None else [],
           "digests": digests}
    path = os.path.join(outdir, name, "verdict.json")
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2, default=str)
    return doc


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--outdir", default="./chaos_smoke_out")
    ap.add_argument("--target_steps", type=int, default=8)
    ap.add_argument("--save_every", type=int, default=2)
    ap.add_argument("--kill_step", type=int, default=5)
    ap.add_argument("--heavy", action="store_true",
                    help="include the hang-detection scenario (liveness "
                    "timeouts dominate its runtime)")
    ap.add_argument("--scenarios", default="",
                    help="comma-separated subset (default: the fast set)")
    args = ap.parse_args(argv)
    outdir = os.path.abspath(args.outdir)
    os.makedirs(outdir, exist_ok=True)
    # The persistent XLA compile cache is the near-zero-compile REJOIN story
    # on real hardware (docs/RESILIENCE.md), but on the CPU mesh a cache HIT
    # in a multi-process gloo run deserializes executables that corrupt
    # memory (segfault/abort/garbage collectives — observed on jax 0.4.37;
    # a respawned worker re-reading the gang's own cache died every epoch).
    # The smoke therefore runs cache-off; chaos_worker keeps the
    # --compile_cache flag for the hardware path.
    cache = ""
    # the agent's degrade.* counters live in THIS process (the smoke IS
    # the agent host); without a configured tracer they drop silently
    obs_configure()
    configure_recorder(os.path.join(outdir, "flight"),
                       min_dump_interval_s=0.0)
    target, save_every, kill_at = (args.target_steps, args.save_every,
                                   args.kill_step)
    t_all = time.time()

    wanted = set(filter(None, args.scenarios.split(",")))

    def enabled(name):
        return not wanted or name in wanted

    summaries = []

    # -- reference: uninterrupted 2-process run -> the bitwise oracle ------
    agent, ref = run_pod("reference2", outdir, cache, nproc=2,
                         target=target, save_every=0)
    ref_digest = next(iter(ref.values()))["digest"] if ref else None
    ok = check(len(ref) == 2 and len({d["digest"] for d in ref.values()}) == 1,
               "reference2: both workers agree on the state digest")
    if not ok:
        print(tail_logs(os.path.join(outdir, "reference2")))
    summaries.append(verdict(outdir, "reference2", agent, ref,
                             {"agree": ok}))

    def assert_recovered(name, agent, digests, *, expect_world=2,
                         expect_reconfigure=True, ref_d=None,
                         restored_below=None):
        """The shared recovery checks: every surviving worker completed,
        digests agree with the reference, recovery actually resumed from a
        durable step rather than restarting from scratch."""
        checks = {}
        ref_d = ref_d if ref_d is not None else ref_digest
        limit = kill_at if restored_below is None else restored_below
        got = {d["digest"] for d in digests.values()}
        checks["bitwise_resume"] = check(
            bool(digests) and got == {ref_d},
            f"{name}: post-recovery state BITWISE-identical to the "
            f"uninterrupted reference at step {target}")
        if expect_reconfigure:
            kinds = [e["kind"] for e in agent.events]
            checks["reconfigured"] = check(
                "reconfigure" in kinds,
                f"{name}: the agent reshaped the pod (events: {kinds})")
            checks["resumed_durable"] = check(
                all(d.get("restored_from") is not None
                    and 0 < d["restored_from"] < limit
                    or d.get("epoch", 0) == 0
                    for d in digests.values())
                and any(d.get("restored_from") is not None
                        for d in digests.values()),
                f"{name}: recovery resumed from a durable step < "
                f"{limit}, not from scratch")
        checks["world_size"] = check(
            all(d["world_size"] == expect_world for d in digests.values()),
            f"{name}: completed at world size {expect_world}")
        if not all(checks.values()):
            print(tail_logs(os.path.join(outdir, name)))
        return checks

    # -- kill_respawn: the acceptance scenario ------------------------------
    if enabled("kill_respawn"):
        plan = FaultPlan([Fault(kind="kill", step=kill_at, rank=1,
                                signal="SIGKILL")])
        agent, digests = run_pod("kill_respawn", outdir, cache, nproc=2,
                                 target=target, save_every=save_every,
                                 plan=plan)
        checks = assert_recovered("kill_respawn", agent, digests)
        checks["worker_lost"] = check(
            any(e["kind"] == "worker_lost" and e.get("worker") == 1
                for e in agent.events),
            "kill_respawn: the agent saw worker 1 die")
        summaries.append(verdict(outdir, "kill_respawn", agent, digests,
                                 checks))
        dump_recorder("kill_respawn")

    # -- kill_sigterm: graceful-preemption contract -------------------------
    if enabled("kill_sigterm"):
        # rank=-1: real preemption SIGTERMs every host at once, and the
        # orbax save barrier needs the whole gang saving the same boundary
        plan = FaultPlan([Fault(kind="kill", step=kill_at, rank=-1,
                                signal="SIGTERM")])
        agent, digests = run_pod("kill_sigterm", outdir, cache, nproc=2,
                                 target=target, save_every=save_every,
                                 plan=plan)
        # the latch lands while step kill_at+1 is in flight (the hook runs
        # at the top of that iteration): the graceful save is at kill_at+1
        boundary = kill_at + 1
        checks = assert_recovered("kill_sigterm", agent, digests,
                                  restored_below=boundary + 1)
        ckpt_dir = os.path.join(outdir, "kill_sigterm", "ckpt")
        checks["graceful_save"] = check(
            os.path.isdir(os.path.join(ckpt_dir, str(boundary))),
            f"kill_sigterm: SIGTERM victims finished the in-flight step "
            f"and left a durable checkpoint at step {boundary}")
        summaries.append(verdict(outdir, "kill_sigterm", agent, digests,
                                 checks))

    # -- flaky coordinator connect: absorbed by retry, not a crash ----------
    if enabled("coordinator_flaky"):
        plan = FaultPlan([Fault(kind="fail_io", site="coordinator_connect",
                                rank=1, times=2)])
        agent, digests = run_pod("coordinator_flaky", outdir, cache,
                                 nproc=2, target=target,
                                 save_every=save_every, plan=plan)
        checks = assert_recovered("coordinator_flaky", agent, digests,
                                  expect_reconfigure=False)
        cs = counters_of(digests)
        checks["absorbed"] = check(
            agent.reconfigures == 0
            and cs.get('retry.attempts_total{op="coordinator_connect"}',
                       0) >= 2
            and cs.get('retry.recovered_total{op="coordinator_connect"}',
                       0) >= 1,
            "coordinator_flaky: injected connect failures absorbed by the "
            f"retry layer (counters: { {k: v for k, v in cs.items() if 'retry' in k} })")
        summaries.append(verdict(outdir, "coordinator_flaky", agent,
                                 digests, checks))

    # -- flaky checkpoint I/O: absorbed by retry ----------------------------
    if enabled("ckpt_io_flaky"):
        plan = FaultPlan([Fault(kind="fail_io", site="ckpt_save",
                                rank=0, times=2)])
        agent, digests = run_pod("ckpt_io_flaky", outdir, cache, nproc=2,
                                 target=target, save_every=save_every,
                                 plan=plan)
        checks = assert_recovered("ckpt_io_flaky", agent, digests,
                                  expect_reconfigure=False)
        cs = counters_of(digests)
        checks["absorbed"] = check(
            agent.reconfigures == 0
            and cs.get('retry.attempts_total{op="ckpt_save"}', 0) >= 2
            and cs.get('retry.recovered_total{op="ckpt_save"}', 0) >= 1,
            "ckpt_io_flaky: injected checkpoint-save failures absorbed by "
            "the retry layer")
        summaries.append(verdict(outdir, "ckpt_io_flaky", agent, digests,
                                 checks))

    # -- corrupt newest checkpoint + kill: fallback restore -----------------
    if enabled("corrupt_recover"):
        ckpt_dir = os.path.join(outdir, "corrupt_recover", "ckpt")
        plan = FaultPlan([
            Fault(kind="corrupt_ckpt", step=kill_at, rank=1, path=ckpt_dir,
                  mode="garbage"),
            Fault(kind="kill", step=kill_at, rank=1, signal="SIGKILL"),
        ])
        # --sync_ckpt: the scenario scripts against "the newest durable
        # step is kill_at-1's boundary save", which async finalize would
        # make racy
        agent, digests = run_pod("corrupt_recover", outdir, cache, nproc=2,
                                 target=target, save_every=save_every,
                                 plan=plan, extra_args=("--sync_ckpt",))
        checks = assert_recovered("corrupt_recover", agent, digests)
        # durable evidence, not counters: the epoch that EXPERIENCED the
        # fallback may not be the epoch that completes and reports
        corrupted_step = kill_at - 1          # last durable boundary save
        checks["fallback"] = check(
            bool(glob.glob(os.path.join(ckpt_dir, "*.corrupt")))
            and all(d.get("restored_from") is not None
                    and d["restored_from"] < corrupted_step
                    for d in digests.values()),
            f"corrupt_recover: restore fell back PAST the corrupted step "
            f"{corrupted_step} (quarantined on disk) to an older durable "
            f"step")
        summaries.append(verdict(outdir, "corrupt_recover", agent, digests,
                                 checks))
        dump_recorder("corrupt_recover")

    # -- shrink: reshape to world size 1 with resharding restore ------------
    if enabled("shrink"):
        plan = FaultPlan([Fault(kind="kill", step=kill_at, rank=1,
                                signal="SIGKILL")])
        agent, digests = run_pod("shrink", outdir, cache, nproc=2,
                                 target=target, save_every=save_every,
                                 plan=plan, policy="shrink")
        # crossing world sizes changes reduction grouping, so the bitwise
        # oracle holds topology fixed: a clean single-process leg pinned to
        # the SAME restore step over a copy of the pod's checkpoints
        w0 = digests.get("w0", {})
        restored_from = w0.get("restored_from")
        ref_d = None
        if restored_from is not None:
            ref_dir = os.path.join(outdir, "shrink_ref")
            shutil.rmtree(ref_dir, ignore_errors=True)
            os.makedirs(ref_dir)
            shutil.copytree(os.path.join(outdir, "shrink", "ckpt"),
                            os.path.join(ref_dir, "ckpt"))
            log = open(os.path.join(ref_dir, "ref.log"), "w")
            rc = subprocess.run(
                [sys.executable, WORKER, "--run_dir", ref_dir,
                 "--target_steps", str(target), "--save_every", "0",
                 "--restore_step", str(restored_from),
                 "--reference", "--compile_cache", cache],
                env=child_env(), stdout=log, stderr=subprocess.STDOUT,
                cwd=ROOT).returncode
            refs = read_digests(ref_dir)
            ref_d = (next(iter(refs.values()))["digest"]
                     if rc == 0 and refs else None)
        checks = {}
        checks["shrunk"] = check(
            w0.get("world_size") == 1 and agent.reconfigures >= 1,
            "shrink: pod reshaped to world size 1 and completed")
        checks["reshard_resume"] = check(
            restored_from is not None and 0 < restored_from < kill_at,
            f"shrink: survivor restored a durable 2-process checkpoint "
            f"(step {restored_from}) onto the 1-device mesh")
        checks["bitwise_vs_pinned_ref"] = check(
            ref_d is not None and w0.get("digest") == ref_d,
            "shrink: recovered state BITWISE-identical to a clean "
            "single-process run pinned to the same restore step")
        if not all(checks.values()):
            print(tail_logs(os.path.join(outdir, "shrink")))
        summaries.append(verdict(outdir, "shrink", agent, digests, checks))

    # -- straggler_reshape: the graftward ladder — page → drain → reshape ---
    # (docs/RESILIENCE.md "Degradation ladder"). A chaos slow fault makes
    # worker 1 a HOST-SIDE straggler: every fleet step stretches to its
    # pace (lockstep collectives), so step rate and arrival phase are
    # identical across the pod — the distinguishing signal is the WAIT
    # INVERSION the heartbeats now carry (blocked_s: the peer waits ~the
    # full injected delay at the collective, the victim waits ~nothing).
    # The agent pages, escalates to a drain (SIGTERM gang → graceful
    # boundary saves), and reshapes WITHOUT the straggler; the survivor's
    # post-recovery state must be bitwise a clean single-proc run pinned
    # to the same restore step (the shrink oracle — topology held fixed).
    if enabled("straggler_reshape"):
        target_sr = max(target, 20)
        plan = FaultPlan([Fault(kind="slow", step=2, rank=1,
                                duration_s=0.8, span_steps=400)])
        monitor = DegradeMonitor(
            StragglerDetector(factor=0.4, sustain=2, warmup_steps=2,
                              min_deficit_s=0.2),
            straggler_escalate=1)
        agent, digests = run_pod("straggler_reshape", outdir, cache,
                                 nproc=2, target=target_sr,
                                 save_every=save_every, plan=plan,
                                 degrade=monitor)
        w0 = digests.get("w0", {})
        restored_from = w0.get("restored_from")
        ref_d = None
        if restored_from is not None:
            ref_dir = os.path.join(outdir, "straggler_ref")
            shutil.rmtree(ref_dir, ignore_errors=True)
            os.makedirs(ref_dir)
            shutil.copytree(os.path.join(outdir, "straggler_reshape",
                                         "ckpt"),
                            os.path.join(ref_dir, "ckpt"))
            log = open(os.path.join(ref_dir, "ref.log"), "w")
            rc = subprocess.run(
                [sys.executable, WORKER, "--run_dir", ref_dir,
                 "--target_steps", str(target_sr), "--save_every", "0",
                 "--restore_step", str(restored_from),
                 "--reference", "--compile_cache", cache],
                env=child_env(), stdout=log, stderr=subprocess.STDOUT,
                cwd=ROOT).returncode
            refs = read_digests(ref_dir)
            ref_d = (next(iter(refs.values()))["digest"]
                     if rc == 0 and refs else None)
        checks = {}
        checks["paged"] = check(
            any(e["kind"] == "worker_paged" and e.get("worker") == 1
                and e.get("reason") == "straggler" for e in agent.events),
            "straggler_reshape: the ladder PAGED the slow worker first "
            "(log/page rung, no membership change)")
        checks["drained"] = check(
            any(e["kind"] == "degrade_drain" and e.get("worker") == 1
                and e.get("reason") == "straggler" for e in agent.events),
            "straggler_reshape: sustained verdict escalated to a drain")
        checks["reshaped"] = check(
            agent.epoch is not None and agent.epoch.members == [0]
            and w0.get("world_size") == 1,
            "straggler_reshape: pod reshaped WITHOUT the straggler "
            f"(members {agent.epoch.members if agent.epoch else None})")
        checks["resumed_durable"] = check(
            restored_from is not None and restored_from > 0,
            f"straggler_reshape: survivor resumed a durable graceful save "
            f"(step {restored_from}), not from scratch")
        checks["bitwise_vs_pinned_ref"] = check(
            ref_d is not None and w0.get("digest") == ref_d,
            "straggler_reshape: post-recovery state BITWISE-identical to "
            "a clean single-process run pinned to the same restore step")
        snap = metrics_snapshot()
        checks["degrade_counters"] = check(
            snap.get('degrade.actions_total{reason="straggler"}', 0) >= 1
            and snap.get('degrade.pages_total{reason="straggler"}', 0) >= 1,
            "straggler_reshape: degrade.{pages,actions}_total{reason="
            "straggler} counters recorded the ladder")
        if not all(checks.values()):
            print(tail_logs(os.path.join(outdir, "straggler_reshape")))
        summaries.append(verdict(outdir, "straggler_reshape", agent,
                                 digests, checks))
        dump_recorder("straggler_reshape")

    # -- hang detection (heavy: dominated by liveness timeouts) -------------
    if args.heavy and enabled("hang_detect"):
        plan = FaultPlan([Fault(kind="hang", step=kill_at, rank=1,
                                duration_s=600.0)])
        agent, digests = run_pod("hang_detect", outdir, cache, nproc=2,
                                 target=target, save_every=save_every,
                                 plan=plan, hb_timeout_s=3.0,
                                 peer_timeout_s=3.0, term_grace_s=3.0)
        checks = assert_recovered("hang_detect", agent, digests)
        checks["hang_seen"] = check(
            any(e["kind"] in ("worker_hung", "worker_lost")
                for e in agent.events),
            "hang_detect: liveness (not an exit code) caught the hang")
        summaries.append(verdict(outdir, "hang_detect", agent, digests,
                                 checks))

    # -- summary -------------------------------------------------------------
    # agent-side registry snapshot (degrade.*/elastic.* counters) as a
    # metrics artifact: `obs_report <outdir>` then renders the DEGRADE
    # verdict over the same files CI uploads
    with open(os.path.join(outdir, "metrics.jsonl"), "w",
              encoding="utf-8") as fh:
        fh.write(json.dumps({"step": 0, **metrics_snapshot()}) + "\n")
    summary = {"ok": not FAILURES, "failures": FAILURES,
               "elapsed_s": round(time.time() - t_all, 1),
               "scenarios": {s["scenario"]: s["ok"] for s in summaries}}
    with open(os.path.join(outdir, "summary.json"), "w",
              encoding="utf-8") as fh:
        json.dump(summary, fh, indent=2)
    print(f"\nchaos_smoke: {'GREEN' if not FAILURES else 'FAILED'} "
          f"({len(summaries)} scenarios, {summary['elapsed_s']}s)"
          + (f"\n  failures: {FAILURES}" if FAILURES else ""))
    return 1 if FAILURES else 0


if __name__ == "__main__":
    sys.exit(main())
