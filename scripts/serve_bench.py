#!/usr/bin/env python
"""Offered-load sweep: static batching vs the continuous-batching engine.

Both servers consume the SAME Poisson arrival trace through the same
``RequestQueue``:

  * **static** — the pre-serve pattern this PR replaces: grab what is
    queued (up to B), pad the batch to B, run the whole
    ``generate_images_tokens`` program end-to-end, drain, repeat. Arrivals
    during a batch wait for the full drain; a partial grab burns empty
    slots for the entire batch.
  * **continuous** — ``dalle_tpu.serve.DecodeEngine``: B shared-cache
    slots, iteration-level refill, per-row lengths.

Reported per mode: completed requests/s, decoded tokens/s, request latency
p50/p95, TTFT p50/p95, and slot occupancy. ``--load`` scales the offered
arrival rate relative to measured static capacity (load > 1 = saturating:
the queue is essentially never empty).

Two further sweeps share the harness: ``--candidates`` (graftloom —
grouped candidate decoding vs independent requests) and ``--paged``
(graftpage — a repeated-prompt trace through a dense engine vs the
paged-KV engine at HBM parity, where radix prefix hits skip the prompt
prefill).

CPU mesh (the sandbox's referee): JAX_PLATFORMS=cpu python
scripts/serve_bench.py --small. On-chip: drop --small, raise --slots.
"""

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def percentile(xs, p):
    xs = sorted(xs)
    return xs[min(int(len(xs) * p), len(xs) - 1)] if xs else None


def run_static(gen, params, cfg, queue, n_requests, slots):
    """Greedy static batching over the shared queue — the pre-serve
    pattern: take what is queued (up to B), pad to B, run the whole batch
    end-to-end, drain, repeat. Completions are batch-synchronized; a
    partial grab burns its empty slots for the full batch."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    done = []
    batch_i = 0
    while len(done) < n_requests:
        reqs = queue.take(slots)
        if not reqs:
            if queue.drained:
                break
            queue.wait_nonempty(timeout=0.02)
            continue
        texts = np.zeros((slots, cfg.text_seq_len), np.int32)
        for i, r in enumerate(reqs):
            texts[i, :len(r.text)] = r.text[:cfg.text_seq_len]
        ids = np.asarray(gen(params, jnp.asarray(texts),
                             jax.random.PRNGKey(batch_i)))
        batch_i += 1
        now = time.perf_counter()
        for i, r in enumerate(reqs):
            done.append({"request_id": r.request_id,
                         "tokens": ids[i],
                         "latency_s": now - r.submitted_at,
                         "ttft_s": now - r.submitted_at})
    return done


def bench_candidates(args):
    """Shared-prefix amortization sweep (graftloom): the SAME workload — G
    groups × N candidates of one prompt, Poisson group arrivals — served
    twice through one engine: as N·G INDEPENDENT requests (every candidate
    pays its own prompt prefill) vs as G candidate GROUPS
    (``Request.group_id`` → ``DALLE.serve_refill_shared``: one prefill per
    group, broadcast). Completed images/s is the headline; per-candidate
    tokens are asserted BITWISE identical to independent single-request
    generation in both modes — the speedup buys nothing if the bits move."""
    import jax
    import numpy as np

    from dalle_tpu.config import DalleConfig
    from dalle_tpu.models.dalle import DALLE, init_dalle
    from dalle_tpu.serve import DecodeEngine, RequestQueue

    if args.small:
        # text-heavy on purpose: prefix sharing amortizes the PROMPT
        # prefill, so the measured regime is long prompt / modest grid —
        # the product shape (users write sentences, previews are small).
        # At this shape the measured program costs are window≈12.6ms vs
        # shared≈1.5ms vs 2×step8≈5.4ms → ~2.6× service-rate headroom
        cfg = DalleConfig(num_text_tokens=256, text_seq_len=96, dim=64,
                          depth=2, heads=2, dim_head=32, image_size=16,
                          image_vocab_size=32, image_fmap_size=4)
    else:
        cfg = DalleConfig(num_text_tokens=1000, text_seq_len=64, dim=256,
                          depth=4, heads=4, dim_head=64, image_size=32,
                          image_vocab_size=512, image_fmap_size=8)
    model, params = init_dalle(cfg, jax.random.PRNGKey(0), batch=2)
    N = args.candidates
    G = args.n_groups
    slots = max(args.slots, N)
    eng = DecodeEngine(model, params, slots=slots,
                       steps_per_sync=args.steps_per_sync)
    rng = np.random.RandomState(args.seed)
    texts = [rng.randint(1, cfg.num_text_tokens,
                         (cfg.text_seq_len,)).astype(np.int32)
             for _ in range(G)]

    def group_seed(g, i):
        return args.seed_base + g * N + i

    # bitwise bar: sampled groups against single-request generation
    check_groups = list(range(min(2, G)))
    refs = {}
    for g in check_groups:
        for i in range(N):
            ids = model.apply(params, np.asarray(texts[g][None]),
                              jax.random.PRNGKey(group_seed(g, i)),
                              method=DALLE.generate_images_tokens)
            refs[(g, i)] = np.asarray(ids[0])

    def submit_group(q, g, grouped):
        for i in range(N):
            q.submit(texts[g], seed=group_seed(g, i),
                     group_id=(g if grouped else None),
                     group_size=N, group_index=i)

    def run_one(grouped, groups):
        q = RequestQueue()
        for g in groups:
            submit_group(q, g, grouped)
        q.close()
        return eng.run(q)

    # warm both admission paths + the step program, then calibrate the
    # arrival process off the GROUPED (faster) mode's STEADY-STATE service
    # time: at load > 1 relative to the fast mode, BOTH modes stay
    # backlogged, so the measured ratio is service-bound throughput —
    # calibrating off the slow mode would leave the fast one
    # arrival-starved and compress the speedup toward 1 regardless of the
    # prefill savings. Amortizing over several closed-queue groups keeps
    # run()'s per-call setup (state init + an eval_shape trace) out of the
    # per-group estimate, which would otherwise inflate inter-arrivals the
    # same way.
    run_one(True, range(min(4, G)))
    run_one(False, range(min(4, G)))

    def timed(groups):
        best = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            run_one(True, groups)
            best = min(best, time.perf_counter() - t0)
        return best
    # difference calibration: a run() pays a fixed setup (state init + an
    # eval_shape trace) that would otherwise inflate the per-group estimate
    # and leave both replay modes arrival-bound; (t_G − t_1)/(G−1) cancels
    # it exactly
    cal_n = min(8, G)
    t_group = (timed(range(cal_n)) - timed(range(1))) / (cal_n - 1)
    t_group = max(t_group, 1e-4)
    inter_arrival = t_group / args.load
    print(json.dumps({"calibration": {
        "t_group_s": round(t_group, 4),
        "inter_arrival_s": round(inter_arrival, 4),
        "slots": slots, "candidates": N, "groups": G}}), flush=True)

    gaps = rng.exponential(inter_arrival, size=G)
    gaps[0] = 0.0

    def one_trial(grouped):
        q = RequestQueue()

        def producer():
            for g, gap in enumerate(gaps):
                time.sleep(gap)
                submit_group(q, g, grouped)
            q.close()

        th = threading.Thread(target=producer)
        eng.stats = type(eng.stats)()       # fresh counters per trial
        t0 = time.perf_counter()
        th.start()
        done = eng.run(q)
        wall = time.perf_counter() - t0
        th.join()
        by_id = {c.request_id: c for c in done}
        exact = True
        for g in check_groups:
            for i in range(N):
                c = by_id[g * N + i]
                exact &= bool(np.array_equal(c.tokens, refs[(g, i)]))
        assert exact, "tokens diverged from single-request refs"
        lat = sorted(c.latency_s for c in done)
        return {"images": len(done), "wall_s": round(wall, 3),
                "images_per_s": round(len(done) / wall, 3),
                "p50_latency_s": round(percentile(lat, 0.5), 4),
                "p95_latency_s": round(percentile(lat, 0.95), 4),
                "refills": eng.stats.refills,
                "shared_refills": eng.stats.shared_refills,
                "prefills_saved": eng.stats.shared_prefills_saved,
                "tokens_bitwise_exact": exact}

    # best-of-2 per mode, trials interleaved so slow background-load drift
    # on the shared 1-core box hits both modes symmetrically (the same
    # min-of-trials convention the classic calibration uses)
    results = {}
    for trial in range(2):
        for mode, grouped in (("independent", False), ("grouped", True)):
            row = one_trial(grouped)
            best = results.get(mode)
            if best is None or row["images_per_s"] > best["images_per_s"]:
                results[mode] = {"mode": mode, **row}
    for mode in ("independent", "grouped"):
        print(json.dumps(results[mode]), flush=True)

    speedup = (results["grouped"]["images_per_s"]
               / results["independent"]["images_per_s"])
    verdict = {"metric": "serve_bench_candidates_images_per_s_speedup",
               "value": round(speedup, 3), "unit": "x",
               "candidates": N, "load": args.load,
               "grouped_images_per_s": results["grouped"]["images_per_s"],
               "independent_images_per_s":
                   results["independent"]["images_per_s"],
               "prefills_saved": results["grouped"]["prefills_saved"],
               "tokens_bitwise_exact": True}
    print(json.dumps(verdict), flush=True)
    return 0 if (not args.assert_win or speedup >= 1.3) else 1


def bench_paged(args):
    """Prefix-overlap sweep (graftpage): the SAME repeated-prompt Poisson
    trace — P distinct prompts × R repeats each, distinct sampling seeds —
    served through a DENSE engine (every request pays its own prompt
    prefill into a private slab) vs a PAGED engine at **HBM parity** (block
    pool = exactly the dense slab's KV bytes: slots × ceil(total/bt)
    blocks). Repeats radix-hit resident prompt blocks, fork the tail via
    COW and recompute ONE position instead of the whole prompt window, so
    at saturating load the paged engine's service rate — and therefore
    completed req/s and TTFT under backlog — pulls ahead on exactly the
    compute the radix cache skipped. Tokens are asserted BITWISE identical
    to independent single-request generation in both modes; a repeat is
    only a win if its bits don't move."""
    import jax
    import numpy as np

    from dalle_tpu.config import DalleConfig
    from dalle_tpu.models.dalle import DALLE, init_dalle
    from dalle_tpu.serve import DecodeEngine, RequestQueue

    if args.small:
        # text-heavy on purpose (same rationale as the candidates sweep):
        # prefix reuse amortizes the PROMPT prefill, so the measured regime
        # is long prompt / modest grid — the product shape
        cfg = DalleConfig(num_text_tokens=256, text_seq_len=96, dim=64,
                          depth=2, heads=2, dim_head=32, image_size=16,
                          image_vocab_size=32, image_fmap_size=4)
    else:
        cfg = DalleConfig(num_text_tokens=1000, text_seq_len=64, dim=256,
                          depth=4, heads=4, dim_head=64, image_size=32,
                          image_vocab_size=512, image_fmap_size=8)
    model, params = init_dalle(cfg, jax.random.PRNGKey(0), batch=2)
    P = args.n_groups
    R = args.repeats
    n = P * R
    slots = args.slots
    bt = 8
    blocks_per_slot = -(-cfg.total_seq_len // bt)
    # HBM parity: the paged pool holds EXACTLY the dense slab's KV bytes.
    # Overlap is what buys residency headroom at parity — R repeats of a
    # prompt share its full prefix blocks, so live demand stays well under
    # slots × blocks_per_slot whenever the trace actually repeats prompts.
    pool_blocks = slots * blocks_per_slot
    dense = DecodeEngine(model, params, slots=slots,
                         steps_per_sync=args.steps_per_sync)
    paged = DecodeEngine(model, params, slots=slots,
                         steps_per_sync=args.steps_per_sync,
                         kv_block_tokens=bt, kv_pool_blocks=pool_blocks)
    engines = {"dense": dense, "paged": paged}
    rng = np.random.RandomState(args.seed)
    prompts = [rng.randint(1, cfg.num_text_tokens,
                           (cfg.text_seq_len,)).astype(np.int32)
               for _ in range(P)]
    # request i = repeat i // P of prompt i % P: round-robin over prompts,
    # so every prompt's FIRST occurrence (the cold prefill) lands early and
    # the tail of the trace is hit-heavy — the steady state of a serving
    # fleet with a popular-prompt distribution
    order = [(i % P, i // P) for i in range(n)]

    def req_seed(g, i):
        return args.seed_base + g * R + i

    # bitwise bar: every repeat of the first two prompts against
    # single-request generation — radix hits and COW forks included
    check_prompts = list(range(min(2, P)))
    refs = {}
    for g in check_prompts:
        for i in range(R):
            ids = model.apply(params, np.asarray(prompts[g][None]),
                              jax.random.PRNGKey(req_seed(g, i)),
                              method=DALLE.generate_images_tokens)
            refs[(g, i)] = np.asarray(ids[0])

    def run_closed(eng, k):
        q = RequestQueue()
        for rid in range(k):
            g, i = order[rid]
            q.submit(prompts[g], seed=req_seed(g, i), request_id=rid)
        q.close()
        return eng.run(q)

    # warm every program out of the timed runs. The paged set is wider
    # than dense (bulk refill + per-width prefill chunks + cow_copy + the
    # width-1 hit recompute), so the warmup mixes a burst with trickled
    # fresh-and-repeat arrivals — the same recipe serve_smoke's
    # zero-compile phase locks in.
    for eng in engines.values():
        run_closed(eng, min(slots + 2, n))
        wq = RequestQueue()
        wq.submit(prompts[0], seed=req_seed(0, 0), request_id=0)

        def warm_producer():
            for rid, (g, i) in ((1, (min(1, P - 1), R - 1)),
                                (2, (0, R - 1))):
                time.sleep(0.05)
                wq.submit(prompts[g], seed=req_seed(g, i), request_id=rid)
            wq.close()

        th = threading.Thread(target=warm_producer)
        th.start()
        eng.run(wq)
        th.join()

    # difference calibration off the PAGED (fast) mode, same convention as
    # the candidates sweep: (t_k − t_1)/(k − 1) cancels run()'s fixed
    # setup cost; load > 1 relative to the fast mode keeps BOTH modes
    # backlogged, so the measured ratio is service-bound throughput
    def timed_closed(k):
        best = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            run_closed(paged, k)
            best = min(best, time.perf_counter() - t0)
        return best
    cal_n = min(2 * slots, n)
    t_req = (timed_closed(cal_n) - timed_closed(1)) / (cal_n - 1)
    t_req = max(t_req, 1e-4)
    inter_arrival = t_req / args.load
    print(json.dumps({"calibration": {
        "t_req_s": round(t_req, 4),
        "inter_arrival_s": round(inter_arrival, 4),
        "slots": slots, "prompts": P, "repeats": R,
        "block_tokens": bt, "pool_blocks": pool_blocks,
        "dense_slab_blocks_equiv": slots * blocks_per_slot}}), flush=True)

    gaps = rng.exponential(inter_arrival, size=n)
    gaps[0] = 0.0

    def one_trial(mode):
        eng = engines[mode]
        q = RequestQueue()

        def producer():
            for rid, gap in enumerate(gaps):
                time.sleep(gap)
                g, i = order[rid]
                q.submit(prompts[g], seed=req_seed(g, i), request_id=rid)
            q.close()

        th = threading.Thread(target=producer)
        eng.stats = type(eng.stats)()       # fresh counters per trial
        t0 = time.perf_counter()
        th.start()
        done = eng.run(q)
        wall = time.perf_counter() - t0
        th.join()
        by_id = {c.request_id: c for c in done}
        exact = True
        for rid, (g, i) in enumerate(order):
            if g in check_prompts:
                exact &= bool(np.array_equal(by_id[rid].tokens,
                                             refs[(g, i)]))
        assert exact, f"{mode}: tokens diverged from single-request refs"
        lat = [c.latency_s for c in done]
        ttft = [c.ttft_s for c in done]
        row = {"mode": mode, "requests": len(done),
               "wall_s": round(wall, 3),
               "completed_per_s": round(len(done) / wall, 3),
               "p50_latency_s": round(percentile(lat, 0.5), 4),
               "p95_latency_s": round(percentile(lat, 0.95), 4),
               "p50_ttft_s": round(percentile(ttft, 0.5), 4),
               "p95_ttft_s": round(percentile(ttft, 0.95), 4),
               "slot_occupancy": round(eng.stats.occupancy_while_queued, 4),
               "tokens_bitwise_exact": exact}
        if mode == "paged":
            row.update({"radix_full_hits": eng.stats.radix_full_hits,
                        "radix_partial_hits": eng.stats.radix_partial_hits,
                        "prefix_hit_tokens": eng.stats.prefix_hit_tokens,
                        "cow_forks": eng.stats.cow_forks,
                        "pages_evicted": eng.stats.pages_evicted})
        return row

    # best-of-2 per mode, trials interleaved so background-load drift on
    # the shared box hits both modes symmetrically
    results = {}
    for trial in range(2):
        for mode in ("dense", "paged"):
            row = one_trial(mode)
            best = results.get(mode)
            if best is None or row["completed_per_s"] > best["completed_per_s"]:
                results[mode] = row
    for mode in ("dense", "paged"):
        print(json.dumps(results[mode]), flush=True)

    speedup = (results["paged"]["completed_per_s"]
               / results["dense"]["completed_per_s"])
    ttft_win = (results["paged"]["p95_ttft_s"]
                < results["dense"]["p95_ttft_s"])
    verdict = {"metric": "serve_bench_paged_req_per_s_speedup",
               "value": round(speedup, 3), "unit": "x",
               "load": args.load, "prompts": P, "repeats": R,
               "hbm_parity_pool_blocks": pool_blocks,
               "paged_req_per_s": results["paged"]["completed_per_s"],
               "dense_req_per_s": results["dense"]["completed_per_s"],
               "ttft_p95_dense_s": results["dense"]["p95_ttft_s"],
               "ttft_p95_paged_s": results["paged"]["p95_ttft_s"],
               "ttft_p95_win": ttft_win,
               "radix_full_hits": results["paged"]["radix_full_hits"],
               "prefix_hit_tokens": results["paged"]["prefix_hit_tokens"],
               "cow_forks": results["paged"]["cow_forks"],
               "tokens_bitwise_exact": True}
    print(json.dumps(verdict), flush=True)
    return 0 if (not args.assert_win
                 or (speedup >= 1.3 and ttft_win)) else 1


def bench(args):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dalle_tpu.config import DalleConfig
    from dalle_tpu.models.dalle import DALLE, init_dalle
    from dalle_tpu.serve import DecodeEngine, RequestQueue

    if args.small:
        cfg = DalleConfig(num_text_tokens=64, text_seq_len=8, dim=64,
                          depth=2, heads=2, dim_head=32, image_size=16,
                          image_vocab_size=32, image_fmap_size=4)
    else:
        # large enough that per-step COMPUTE dominates per-dispatch host
        # overhead on the 1-core CPU mesh (~8.4 ms engine step vs ~8.7 ms
        # static per-step-equivalent at this shape) — the regime real
        # accelerators are always in
        cfg = DalleConfig(num_text_tokens=1000, text_seq_len=32, dim=256,
                          depth=4, heads=4, dim_head=64, image_size=32,
                          image_vocab_size=512, image_fmap_size=8)
    model, params = init_dalle(cfg, jax.random.PRNGKey(0), batch=2)
    rng = np.random.RandomState(args.seed)
    texts = [rng.randint(1, cfg.num_text_tokens,
                         (cfg.text_seq_len,)).astype(np.int32)
             for _ in range(args.n_requests)]
    # ragged service demand (the serving-realistic default): partial-grid
    # requests decode U[n/4, n] image tokens (previews / progressive
    # decode / top-rows-for-inpainting). The static path has no per-row
    # early exit — every row decodes the full grid in lockstep and
    # finished rows burn forward passes; the engine retires each row at
    # its own length and refills the slot.
    if args.fixed_lengths:
        lengths = [cfg.image_seq_len] * args.n_requests
    else:
        lengths = [int(rng.randint(cfg.image_seq_len // 4,
                                   cfg.image_seq_len + 1))
                   for _ in range(args.n_requests)]

    @jax.jit
    def gen(p, t, k):
        return model.apply(p, t, k, method="generate_images_tokens")

    eng = DecodeEngine(model, params, slots=args.slots,
                       steps_per_sync=args.steps_per_sync)

    # warm BOTH paths (compiles out of the timed runs), then calibrate
    # static capacity from a warm full batch
    dummy = jnp.asarray(np.stack([t for t in texts[:args.slots]]))
    np.asarray(gen(params, dummy, jax.random.PRNGKey(0)))
    # slots+2 requests with one short row: warms the bulk refill window,
    # the trickle (per-row scatter-prefill) path AND the step program
    warm_q = RequestQueue()
    for i in range(args.slots + 2):
        warm_q.submit(texts[i % args.n_requests], seed=i, request_id=i,
                      max_tokens=cfg.image_seq_len // 4 if i == 0 else None)
    warm_q.close()
    eng.run(warm_q)
    t_batch = float("inf")
    for r in (1, 2):                           # min-of-2: 1-core box noise
        t0 = time.perf_counter()
        np.asarray(gen(params, dummy, jax.random.PRNGKey(r)))
        t_batch = min(t_batch, time.perf_counter() - t0)
    capacity = args.slots / t_batch            # req/s at full static batches
    inter_arrival = 1.0 / (capacity * args.load)
    print(json.dumps({"calibration": {"t_batch_s": round(t_batch, 3),
                                      "static_capacity_rps": round(capacity, 3),
                                      "inter_arrival_s": round(inter_arrival, 4)}}),
          flush=True)

    # shared arrival trace (relative offsets, replayed per mode)
    gaps = rng.exponential(inter_arrival, size=args.n_requests)
    gaps[0] = 0.0

    def producer(queue):
        for i, gap in enumerate(gaps):
            time.sleep(gap)
            queue.submit(texts[i], seed=args.seed_base + i, request_id=i,
                         max_tokens=lengths[i])
        queue.close()

    results = {}
    for mode in ("static", "continuous"):
        q = RequestQueue()
        th = threading.Thread(target=producer, args=(q,))
        t0 = time.perf_counter()
        th.start()
        if mode == "static":
            done = run_static(gen, params, cfg, q, args.n_requests,
                              args.slots)
            occupancy = None
        else:
            completed = eng.run(q)
            done = [{"request_id": c.request_id, "tokens": c.tokens,
                     "latency_s": c.latency_s, "ttft_s": c.ttft_s}
                    for c in completed]
            occupancy = round(eng.stats.occupancy_while_queued, 4)
        wall = time.perf_counter() - t0
        th.join()
        lat = [d["latency_s"] for d in done]
        ttft = [d["ttft_s"] for d in done]
        n_tok = sum(lengths[d["request_id"]] for d in done)
        row = {"mode": mode, "slots": args.slots,
               "requests": len(done), "wall_s": round(wall, 3),
               "completed_per_s": round(len(done) / wall, 3),
               "tok_per_s": round(n_tok / wall, 1),
               "p50_latency_s": round(percentile(lat, 0.5), 4),
               "p95_latency_s": round(percentile(lat, 0.95), 4),
               "p50_ttft_s": round(percentile(ttft, 0.5), 4),
               "p95_ttft_s": round(percentile(ttft, 0.95), 4),
               "slot_occupancy": occupancy}
        results[mode] = row
        print(json.dumps(row), flush=True)

    speedup = (results["continuous"]["completed_per_s"]
               / results["static"]["completed_per_s"])
    verdict = {"metric": "serve_bench_offered_load", "load": args.load,
               "continuous_over_static_rps": round(speedup, 3),
               "continuous_wins": speedup > 1.0}
    print(json.dumps(verdict), flush=True)
    return 0 if (not args.assert_win or speedup > 1.0) else 1


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--n_requests", type=int, default=64)
    ap.add_argument("--load", type=float, default=1.15,
                    help="offered arrival rate / measured static capacity "
                         "(≥1 = saturating: the queue stays nonempty)")
    ap.add_argument("--steps_per_sync", type=int, default=8,
                    help="engine device steps per host sync (amortizes "
                         "dispatch overhead; admission granularity)")
    ap.add_argument("--fixed_lengths", action="store_true",
                    help="every request decodes the full grid (parity "
                         "regime: static scan vs engine, no ragged win)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--seed_base", type=int, default=5000,
                    help="per-request sampling seeds = seed_base + i")
    ap.add_argument("--small", action="store_true",
                    help="tiny config for the CPU mesh")
    ap.add_argument("--assert_win", dest="assert_win", action="store_true",
                    help="exit 1 unless continuous beats static on "
                         "completed requests/s (candidates mode: unless "
                         "grouped ≥ 1.3× independent images/s)")
    ap.add_argument("--candidates", type=int, default=0,
                    help="shared-prefix sweep: serve G groups × N "
                         "candidates grouped (one prefill per group) vs as "
                         "independent requests; reports completed images/s "
                         "+ the amortization ledger (graftloom)")
    ap.add_argument("--n_groups", type=int, default=16,
                    help="candidate-mode group count / paged-mode distinct "
                         "prompt count")
    ap.add_argument("--paged", action="store_true",
                    help="prefix-overlap sweep: serve a repeated-prompt "
                         "trace dense vs paged-KV at HBM parity; reports "
                         "completed req/s + TTFT p95 + the radix ledger "
                         "(graftpage). --assert_win requires paged ≥ 1.3× "
                         "dense req/s AND a TTFT p95 win")
    ap.add_argument("--repeats", type=int, default=12,
                    help="paged-mode repeats per distinct prompt")
    args = ap.parse_args(argv)
    if args.paged:
        return bench_paged(args)
    if args.candidates and args.candidates > 1:
        return bench_candidates(args)
    return bench(args)


if __name__ == "__main__":
    sys.exit(main())
