#!/usr/bin/env python
"""Gateway offered-load bench: admission + policy behavior under overload.

Drives a loopback gateway (tiny model, CPU mesh by default — run on real
hardware for absolute numbers) with Poisson arrivals from two tenants, one
latency-sensitive (deadline + high priority) and one batch (no deadline),
at a configurable load factor. Reports per-tenant completed/s, TTFT
p50/p95, reject and shed counts — once under FIFO and once under
priority_deadline, so the policy's effect is a single diff:

    python scripts/gateway_bench.py --load 1.5 --requests 40

Expected shape (and what the PR measured at load 1.5, CPU mesh): FIFO
serves arrival order, so latency-tenant p95 TTFT tracks the whole backlog;
priority_deadline serves the latency tenant first and sheds already-missed
deadlines instead of burning slots on them — latency-tenant TTFT drops,
batch tenant pays, total goodput holds or rises.
"""

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_trial(policy_name: str, args, engines, texts, arrivals):
    from dalle_tpu.gateway import (AdmissionController, Gateway, Replica,
                                   ReplicaRouter, TenantQuotas, iter_sse)
    from dalle_tpu.serve import PriorityDeadlinePolicy

    policy = (PriorityDeadlinePolicy() if policy_name == "priority_deadline"
              else None)
    # engines are pre-warmed and REUSED across trials (a Replica's worker
    # exits at drain; the compiled programs persist) so neither trial pays
    # compile inside its measured window
    replicas = [Replica(eng, replica_id=f"bench-{policy_name}-{i}",
                        maxsize=args.queue_maxsize, policy=policy).start()
                for i, eng in enumerate(engines)]
    admission = AdmissionController(TenantQuotas(rate_per_s=1e6, burst=1e6))
    gw = Gateway(ReplicaRouter(replicas), admission).start()

    results = []
    lock = threading.Lock()

    def client(i, delay):
        time.sleep(delay)
        latency_tenant = i % 2 == 0
        body = {"text": texts[i].tolist(), "seed": 1000 + i,
                "tenant": "latency" if latency_tenant else "batch",
                "priority": 10 if latency_tenant else 0}
        if latency_tenant:
            body["deadline_s"] = args.deadline_s
        import http.client
        host, port = gw.address.split("//")[1].rsplit(":", 1)
        t0 = time.perf_counter()
        conn = http.client.HTTPConnection(host, int(port), timeout=600)
        conn.request("POST", "/v1/generate",
                     json.dumps({**body, "stream": True}))
        resp = conn.getresponse()
        row = {"tenant": body["tenant"], "status": resp.status,
               "outcome": "rejected", "ttft_s": None}
        if resp.status == 200:
            for event, data in iter_sse(resp):
                if event == "row" and row["ttft_s"] is None:
                    row["ttft_s"] = time.perf_counter() - t0
                elif event == "done":
                    row["outcome"] = "done"
                    row["latency_s"] = time.perf_counter() - t0
                elif event == "error":
                    row["outcome"] = data["reason"]
        conn.close()
        with lock:
            results.append(row)

    t_start = time.perf_counter()
    threads = [threading.Thread(target=client, args=(i, d))
               for i, d in enumerate(arrivals)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start
    gw.shutdown(drain=True, timeout=120)

    def pct(vals, q):
        if not vals:
            return None
        vals = sorted(vals)
        return vals[min(int(q * (len(vals) - 1) + 0.5), len(vals) - 1)]

    out = {"policy": policy_name, "wall_s": round(wall, 2)}
    for tenant in ("latency", "batch"):
        rows = [r for r in results if r["tenant"] == tenant]
        done = [r for r in rows if r["outcome"] == "done"]
        ttfts = [r["ttft_s"] for r in done if r["ttft_s"] is not None]
        out[tenant] = {
            "offered": len(rows), "completed": len(done),
            "shed": sum(1 for r in rows if r["outcome"] == "deadline_shed"),
            "rejected": sum(1 for r in rows if r["outcome"] == "rejected"),
            "ttft_p50_s": round(pct(ttfts, 0.5), 3) if ttfts else None,
            "ttft_p95_s": round(pct(ttfts, 0.95), 3) if ttfts else None,
        }
    out["completed_per_s"] = round(
        sum(out[t]["completed"] for t in ("latency", "batch")) / wall, 3)
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--requests", type=int, default=24)
    ap.add_argument("--replicas", type=int, default=1)
    ap.add_argument("--slots", type=int, default=3)
    ap.add_argument("--steps_per_sync", type=int, default=4)
    ap.add_argument("--queue_maxsize", type=int, default=64)
    ap.add_argument("--load", type=float, default=1.5,
                    help="offered load relative to measured capacity "
                         "(>1 = overload, where policy matters)")
    ap.add_argument("--deadline_s", type=float, default=None,
                    help="latency-tenant deadline (default: calibrated to "
                         "2× an unloaded request)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", type=str, default="")
    args = ap.parse_args(argv)

    import jax
    import numpy as np

    from dalle_tpu.config import DalleConfig
    from dalle_tpu.models.dalle import DALLE, init_dalle
    from dalle_tpu.serve import DecodeEngine, RequestQueue

    cfg = DalleConfig(num_text_tokens=32, text_seq_len=6, dim=64, depth=2,
                      heads=2, dim_head=32, image_size=16,
                      image_vocab_size=24, image_fmap_size=4)
    model, params = init_dalle(cfg, jax.random.PRNGKey(args.seed), batch=2)
    rng = np.random.RandomState(args.seed)
    texts = [rng.randint(1, 20, (cfg.text_seq_len,)).astype(np.int32)
             for _ in range(args.requests)]

    # build + warm every engine (compile happens HERE, outside any
    # measured window), then calibrate the warm single-request service time
    engines = [DecodeEngine(model, params, slots=args.slots,
                            steps_per_sync=args.steps_per_sync)
               for _ in range(args.replicas)]
    for eng in engines:
        q = RequestQueue()
        q.submit(texts[0], seed=1000)
        q.close()
        eng.run(q)
    q = RequestQueue()
    q.submit(texts[0], seed=1000)
    q.close()
    t0 = time.perf_counter()
    engines[0].run(q)
    t_req = time.perf_counter() - t0
    capacity = args.slots * args.replicas / t_req      # req/s, roughly
    rate = capacity * args.load
    if args.deadline_s is None:
        args.deadline_s = 2.0 * t_req
    arrivals = np.cumsum(rng.exponential(1.0 / rate, args.requests))
    print(f"calibration: {t_req:.2f}s/req → capacity ≈ {capacity:.2f} "
          f"req/s, offering {rate:.2f} req/s, deadline {args.deadline_s:.2f}s",
          flush=True)

    report = {"requests": args.requests, "load": args.load,
              "deadline_s": round(args.deadline_s, 3),
              "trials": [run_trial(p, args, engines, texts,
                                   arrivals.tolist())
                         for p in ("fifo", "priority_deadline")]}
    print(json.dumps({"metric": "gateway_bench", **report}, indent=2))
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as fh:
            json.dump(report, fh, indent=2)
    return 0


if __name__ == "__main__":
    sys.exit(main())
