#!/usr/bin/env python
"""Attention-core shootout at the DALL·E-small shapes (b64 h8 n512 dh64,
bf16, causal, fwd+bwd): dense attend vs our Pallas flash vs the official
jax.experimental TPU flash_attention and splash_attention. One dispatched
scan per candidate. Source of docs/PERF_SMALL.md's kernel table."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def timed(fn, args, k=8):
    @jax.jit
    def many(args):
        def body(c, _):
            a = tuple(x + jnp.asarray(1e-12 * c, x.dtype) for x in args)
            g = jax.grad(
                lambda *a: jnp.sum(fn(*a).astype(jnp.float32) ** 2),
                argnums=0)(*a)
            return c + 1e-30 * jnp.sum(g.astype(jnp.float32)), None

        c, _ = jax.lax.scan(body, jnp.float32(0.0), None, length=k)
        return c

    float(jax.device_get(many(args)))
    t0 = time.perf_counter()
    float(jax.device_get(many(args)))
    return (time.perf_counter() - t0) / k


def main():
    b, h, n, d = 64, 8, 512, 64
    rng = np.random.RandomState(0)
    q, k_, v = (jnp.asarray(rng.standard_normal((b, h, n, d)), jnp.bfloat16)
                for _ in range(3))

    from dalle_tpu.ops.attention import attend
    print("dense_attend      %7.2f ms" % (1e3 * timed(
        lambda q, k, v: attend(q, k, v, causal=True, softmax_f32=False),
        (q, k_, v))))

    from dalle_tpu.ops.flash_attention import flash_attention
    for blk in (128, 256, 512):
        try:
            t = timed(lambda q, k, v, blk=blk: flash_attention(
                q, k, v, causal=True, block_q=blk, block_k=blk), (q, k_, v))
            print("ours_flash_b%-4d  %7.2f ms" % (blk, 1e3 * t))
        except Exception as e:
            print("ours_flash_b%-4d  FAIL %s" % (blk, str(e)[:60]))

    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes, flash_attention as jax_flash)
    for blk in (128, 256, 512):
        try:
            bs = BlockSizes(
                block_q=blk, block_k_major=blk, block_k=blk, block_b=1,
                block_q_major_dkv=blk, block_k_major_dkv=blk,
                block_k_dkv=blk, block_q_dkv=blk,
                block_k_major_dq=blk, block_k_dq=blk, block_q_dq=blk)
            t = timed(lambda q, k, v, bs=bs: jax_flash(
                q, k, v, causal=True, sm_scale=d ** -0.5, block_sizes=bs),
                (q, k_, v))
            print("jax_flash_b%-4d   %7.2f ms" % (blk, 1e3 * t))
        except Exception as e:
            print("jax_flash_b%-4d   FAIL %s" % (blk, str(e)[:60]))

    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as sk, splash_attention_mask as sm)
    mqk = sm.MultiHeadMask([sm.CausalMask((n, n))] * h)
    for blk in (256, 512):
        try:
            bs = sk.BlockSizes(
                block_q=blk, block_kv=blk, block_kv_compute=blk,
                block_q_dkv=blk, block_kv_dkv=blk, block_kv_dkv_compute=blk,
                block_q_dq=blk, block_kv_dq=blk)
            kernel = sk.make_splash_mha(mask=mqk, head_shards=1,
                                        q_seq_shards=1, block_sizes=bs)
            fn = jax.vmap(lambda q, k, v: kernel(q * (d ** -0.5), k, v))
            t = timed(fn, (q, k_, v))
            print("splash_b%-4d      %7.2f ms" % (blk, 1e3 * t))
        except Exception as e:
            print("splash_b%-4d      FAIL %s" % (blk, str(e)[:60]))


if __name__ == "__main__":
    main()
