#!/usr/bin/env python
"""Attention-core shootout at the DALL·E-small shapes (b64 h8 n512 dh64,
bf16, causal, FULL fwd+bwd — gradients wrt q, k AND v, so XLA cannot
dead-code-eliminate the dense arm's dk/dv matmuls while the opaque
custom_vjp kernels compute theirs): dense attend vs our Pallas flash vs the
official jax.experimental TPU flash_attention and splash_attention. One
dispatched scan per candidate (scripts/_bench_util.py). Source of
docs/PERF_SMALL.md's kernel table."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import numpy as np

from _bench_util import timed_scan


def main():
    b, h, n, d = 64, 8, 512, 64
    rng = np.random.RandomState(0)
    import jax.numpy as jnp
    q, k_, v = (jnp.asarray(rng.standard_normal((b, h, n, d)), jnp.bfloat16)
                for _ in range(3))

    from dalle_tpu.ops.attention import attend
    t = timed_scan(lambda q, k, v: attend(q, k, v, causal=True,
                                          softmax_f32=False),
                   (q, k_, v), grad=True)
    print("dense_attend      %7.2f ms" % (1e3 * t))

    from dalle_tpu.ops.flash_attention import flash_attention
    for blk in (128, 256, 512):
        try:
            t = timed_scan(lambda q, k, v, blk=blk: flash_attention(
                q, k, v, causal=True, block_q=blk, block_k=blk),
                (q, k_, v), grad=True)
            print("ours_flash_b%-4d  %7.2f ms" % (blk, 1e3 * t))
        except Exception as e:  # noqa: BLE001 - sweep point: a config
            # the compiler rejects is a FAIL row, not an aborted sweep
            print("ours_flash_b%-4d  FAIL %s" % (blk, str(e)[:60]))

    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes, flash_attention as jax_flash)
    for blk in (128, 256, 512):
        try:
            bs = BlockSizes(
                block_q=blk, block_k_major=blk, block_k=blk, block_b=1,
                block_q_major_dkv=blk, block_k_major_dkv=blk,
                block_k_dkv=blk, block_q_dkv=blk,
                block_k_major_dq=blk, block_k_dq=blk, block_q_dq=blk)
            t = timed_scan(lambda q, k, v, bs=bs: jax_flash(
                q, k, v, causal=True, sm_scale=d ** -0.5, block_sizes=bs),
                (q, k_, v), grad=True)
            print("jax_flash_b%-4d   %7.2f ms" % (blk, 1e3 * t))
        except Exception as e:  # noqa: BLE001 - sweep point (see above)
            print("jax_flash_b%-4d   FAIL %s" % (blk, str(e)[:60]))

    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as sk, splash_attention_mask as sm)
    mqk = sm.MultiHeadMask([sm.CausalMask((n, n))] * h)
    for blk in (256, 512):
        try:
            bs = sk.BlockSizes(
                block_q=blk, block_kv=blk, block_kv_compute=blk,
                block_q_dkv=blk, block_kv_dkv=blk, block_kv_dkv_compute=blk,
                block_q_dq=blk, block_kv_dq=blk)
            kernel = sk.make_splash_mha(mask=mqk, head_shards=1,
                                        q_seq_shards=1, block_sizes=bs)
            fn = jax.vmap(lambda q, k, v: kernel(q * (d ** -0.5), k, v))
            t = timed_scan(fn, (q, k_, v), grad=True)
            print("splash_b%-4d      %7.2f ms" % (blk, 1e3 * t))
        except Exception as e:  # noqa: BLE001 - sweep point (see above)
            print("splash_b%-4d      FAIL %s" % (blk, str(e)[:60]))


if __name__ == "__main__":
    main()
