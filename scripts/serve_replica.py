#!/usr/bin/env python
"""Standalone serving replica: one engine + queue behind the fleet RPC.

The graftfleet unit of capacity AND of failure (docs/SERVING.md
"Deployment topology"): a process hosting one continuous-batching
``DecodeEngine`` + ``PolicyQueue`` (``dalle_tpu/gateway/replica.py``)
served over the length-prefixed frame protocol
(``dalle_tpu/fleet/transport.py``). The gateway dials it through
``RemoteReplica``; the controller (``fleet/controller.py``) spawns, drains
and kills it.

Cold-start contract: with ``--aot_dir`` the engine loads serialized
executables (fingerprint-checked; a mismatch refuses LOUDLY and falls back
to jit), and with ``--warmup`` the process serves one self-request before
printing its handshake — so the moment the parent sees the handshake line,
attach→serving pays ZERO backend compiles (asserted by
scripts/fleet_smoke.py via the compile counter the health verb exposes).

The handshake is ONE JSON line on stdout once the socket is listening:

  {"fleet_replica": 1, "addr": "127.0.0.1:PORT", "pid": ..,
   "replica_id": .., "aot_loaded": bool, "aot_refusal": str|null, ...}

Its field set — like every frame this process sends or reads — is pinned
by the graftwire protocol contract (``contracts/wire.json``, the
``handshake.reply`` channel): adding or renaming a key here fails
``scripts/wire_audit.py --check`` until the golden is regenerated, and a
refused/absent handshake counts ``fleet.protocol_errors_total
{kind="handshake"}`` on the manager side.

Postmortem story matches the gateway process: ``--flight_dir`` configures
a flight recorder (bundles on worker death / SIGQUIT), ``kill -USR2``
captures a bounded jax profile, SIGTERM drains gracefully. A
``DALLE_CHAOS_PLAN`` env plan (dalle_tpu/chaos) is installed on entry and
fires at the engine's decode-iteration boundaries — the fleet smoke
kills/hangs/slows replica processes through it mid-stream.

Run (loopback demo):
  JAX_PLATFORMS=cpu python scripts/serve_replica.py --untrained --port 0
"""

import argparse
import json
import os
import signal
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import (add_compile_cache_args, add_profiler_args,  # noqa: E402
                     enable_compile_cache, install_sigusr2_profiler,
                     load_model_checkpoint)

TINY_CFG = dict(num_text_tokens=32, text_seq_len=6, dim=64, depth=2,
                heads=2, dim_head=32, image_size=16, image_vocab_size=24,
                image_fmap_size=4)


def build_parser():
    ap = argparse.ArgumentParser(description=__doc__)
    src = ap.add_argument_group("model")
    src.add_argument("--dalle_path", type=str, default=None,
                     help="DALLE checkpoint dir (scripts/train_dalle.py)")
    src.add_argument("--untrained", action="store_true",
                     help="tiny random model (TINY_CFG; loopback smoke)")
    src.add_argument("--model_seed", type=int, default=0,
                     help="--untrained init seed — every replica of one "
                          "fleet must use the SAME seed so their params "
                          "(and therefore tokens) are identical")
    src.add_argument("--precision", type=str, default="int8w",
                     choices=["float32", "bfloat16", "bf16_int8kv", "int8w"])
    eng = ap.add_argument_group("engine")
    eng.add_argument("--slots", type=int, default=4)
    eng.add_argument("--steps_per_sync", type=int, default=4)
    eng.add_argument("--queue_maxsize", type=int, default=64)
    eng.add_argument("--prefill_chunk", type=int, default=0)
    eng.add_argument("--kv_block_tokens", type=int, default=0,
                     help="graftpage paged KV: > 0 swaps the dense "
                          "per-slot cache slab for a fixed block pool + "
                          "per-slot page tables (device data — admission "
                          "never recompiles) with radix prefix reuse and "
                          "COW forks; 0 = dense slabs. Mutually exclusive "
                          "with --prefill_chunk (docs/SERVING.md)")
    eng.add_argument("--kv_pool_blocks", type=int, default=None,
                     help="paged pool size in blocks (default slots x "
                          "ceil(total_seq_len / kv_block_tokens) — exact "
                          "HBM parity with the dense slabs; add headroom "
                          "above parity to keep evicted-before-reuse "
                          "prefixes resident)")
    eng.add_argument("--no_radix_cache", dest="radix_cache",
                     action="store_false",
                     help="disable the radix prefix cache (paged engine "
                          "still pages + COW-shares; repeats just stop "
                          "hitting resident prompt blocks)")
    eng.add_argument("--policy", type=str, default="fifo",
                     choices=["fifo", "priority_deadline"])
    eng.add_argument("--decode_health", action="store_true",
                     help="graftpulse decode-quality gauges; exposed via "
                          "the health verb — the controller's drain-on-"
                          "degradation signal")
    eng.add_argument("--wedge_timeout_s", type=float, default=0.0,
                     help="graftward wedged-engine self-detection: a BUSY "
                          "engine whose iteration counter freezes this "
                          "long self-reports unhealthy{reason=wedged} "
                          "through the health verb (the controller then "
                          "drains + replaces with no operator page). Set "
                          "above the longest legitimate single dispatch; "
                          "0 disables — arm it on --aot_dir --warmup "
                          "replicas, where no compile can freeze a busy "
                          "engine (docs/SERVING.md)")
    aot = ap.add_argument_group("AOT cold start")
    aot.add_argument("--aot_dir", type=str, default=None,
                     help="serialized engine executables; fingerprint "
                          "mismatch refuses loudly and falls back to jit")
    aot.add_argument("--warmup", action="store_true",
                     help="serve one self-request before the handshake so "
                          "attach-time serving pays zero compiles")
    net = ap.add_argument_group("network")
    net.add_argument("--host", type=str, default="127.0.0.1")
    net.add_argument("--port", type=int, default=0,
                     help="0 = ephemeral (the handshake reports it)")
    net.add_argument("--replica_id", type=str, default=None)
    scope = ap.add_argument_group("graftscope (docs/OBSERVABILITY.md)")
    scope.add_argument("--flight_dir", type=str, default="flight_bundles",
                       help="flight-recorder bundle dir ('off' disables); "
                            "a per-replica subdir keyed by replica_id "
                            "keeps fleet postmortems separable")
    scope.add_argument("--telemetry_dir", type=str, default=None,
                       help="graftlens per-process telemetry dir (a "
                            "replica_id subdir is created): a daemon "
                            "thread atomically rewrites spans/metrics/"
                            "events every --telemetry_interval_s, so the "
                            "fleet collector can join this process's "
                            "timeline even after a SIGKILL")
    scope.add_argument("--telemetry_interval_s", type=float, default=0.2,
                       help="telemetry flush period (seconds)")
    add_compile_cache_args(ap)
    add_profiler_args(ap)
    return ap


def build_engine(args):
    import jax
    from dalle_tpu.models.wrapper import DalleWithVae
    if args.untrained:
        from dalle_tpu.config import DalleConfig
        from dalle_tpu.models.dalle import init_dalle
        model, params = init_dalle(DalleConfig(**TINY_CFG),
                                   jax.random.PRNGKey(args.model_seed),
                                   batch=2)
        dv = DalleWithVae(model, params, None)
    elif args.dalle_path:
        from dalle_tpu.config import DalleConfig
        from dalle_tpu.models.dalle import init_dalle
        model, params, _ = load_model_checkpoint(args.dalle_path, "DALLE",
                                                 DalleConfig, init_dalle)
        dv = DalleWithVae(model, params, None)
    else:
        raise SystemExit("provide --dalle_path or --untrained")
    return dv.serve_engine(slots=args.slots, precision=args.precision,
                           steps_per_sync=args.steps_per_sync,
                           decode_health=args.decode_health,
                           prefill_chunk=args.prefill_chunk,
                           kv_block_tokens=args.kv_block_tokens,
                           kv_pool_blocks=args.kv_pool_blocks,
                           radix_cache=args.radix_cache)


def warmup(replica, text_seq_len: int) -> None:
    """One self-request through the full submit→stream→done path: after
    this, admission and decode dispatch only already-compiled programs."""
    import numpy as np
    stream = replica.submit(np.zeros((text_seq_len,), np.int32), seed=0,
                            max_tokens=1)
    for kind, _payload in stream.events(timeout=300.0,
                                        still_alive=lambda: replica.healthy):
        if kind != "row":
            break


def main(argv=None):
    args = build_parser().parse_args(argv)
    enable_compile_cache(args)
    install_sigusr2_profiler("profile_artifacts", args)

    from dalle_tpu import obs
    from dalle_tpu.chaos import faults
    from dalle_tpu.fleet import ReplicaServer
    from dalle_tpu.gateway import Replica, fingerprint_mismatch
    from dalle_tpu.serve import PriorityDeadlinePolicy

    obs.configure()
    counter = obs.install_compile_counter()
    rid = args.replica_id or f"replica-{os.getpid()}"
    if args.flight_dir != "off":
        obs.configure_recorder(os.path.join(args.flight_dir, rid),
                               sample_interval_s=1.0)
        obs.install_signal_dump()
    exporter = None
    if args.telemetry_dir:
        exporter = obs.TelemetryExporter(
            os.path.join(args.telemetry_dir, rid),
            interval_s=args.telemetry_interval_s, proc=rid)
    # a parent-scripted fault plan (kill/hang/slow keyed on the engine's
    # decode-iteration counter — serve/engine.py fires chaos.step_hook at
    # every step dispatch, so a fault lands mid-stream, between row
    # commits); no-op without the env var
    faults.install_from_env()

    engine = build_engine(args)
    aot_refusal = (fingerprint_mismatch(engine, args.aot_dir)
                   if args.aot_dir else None)
    replica = Replica(
        engine, replica_id=rid, maxsize=args.queue_maxsize,
        policy=(PriorityDeadlinePolicy() if args.policy ==
                "priority_deadline" else None),
        aot_dir=args.aot_dir).start()
    if args.warmup:
        warmup(replica, engine.text_seq_len)
    watchdog = None
    if args.wedge_timeout_s > 0:
        # the engine-iteration liveness probe (dalle_tpu/degrade/wedge.py):
        # progress = the loop's monotonic dispatch counter, busy = accepted
        # work not yet completed. A trip latches Replica.mark_wedged —
        # healthy goes False, the health verb carries reason="wedged", and
        # the fleet controller's next tick migrate-drains this process.
        from dalle_tpu.degrade import WedgeWatchdog

        def _on_wedge(detail):
            replica.mark_wedged()
            # the replica-side postmortem CI could never see before
            # graftlens: the wedge trips in THIS process, so dump the
            # bundle here (force: the wedge reason must never be
            # rate-limited away) — fleet_smoke collects the replica
            # flight dir into its artifact dir and asserts one lands
            obs.dump_recorder("wedged", force=True)

        watchdog = WedgeWatchdog(
            lambda: (replica.progress or 0, replica.inflight > 0),
            args.wedge_timeout_s,
            on_wedge=_on_wedge).start()
    server = ReplicaServer(replica, host=args.host, port=args.port,
                           compile_counter=counter).start()

    stop = threading.Event()

    def _sigterm(*_):
        stop.set()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, _sigterm)

    print(json.dumps({
        "fleet_replica": 1, "addr": server.addr, "pid": os.getpid(),
        "replica_id": rid, "slots": args.slots,
        "aot_loaded": replica.aot_loaded, "aot_refusal": aot_refusal,
        "warmed": bool(args.warmup),
        "backend_compiles": counter.count}), flush=True)

    stop.wait()
    # graceful preemption: stop accepting, finish accepted work, exit 0
    if watchdog is not None:
        watchdog.stop()
    server.shutdown()
    replica.drain(timeout=60)
    if exporter is not None:
        exporter.close()          # final flush: the drain's spans land too
    obs.disable_recorder()
    return 0


if __name__ == "__main__":
    sys.exit(main())
