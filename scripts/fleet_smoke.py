#!/usr/bin/env python
"""Fleet smoke — the CI gate for dalle_tpu/fleet (docs/SERVING.md
"Deployment topology").

A REAL cross-process fleet on loopback: replica processes
(scripts/serve_replica.py) behind the socket RPC transport, the HTTP
gateway dispatching to them through RemoteReplica, and the SLO-driven
controller closing the loop. Asserts, end to end over real processes:

  * **burst → scale up, zero compiles** — an overload burst breaches the
    burn-rate sentry (queue_full rejects burn the error budget); the
    controller, after its sustain window, attaches a WARM AOT-prespawned
    replica process; goodput (completed/offered) recovers to 1.0 on the
    follow-up burst and the warm replica's backend-compile counter is
    UNCHANGED across attach→serving (the health verb exposes it) — spawn
    to serving paid zero compiles;
  * **mid-stream drain is invisible** — a health-page drain
    (controller.request_drain → migrate) fires while a request is
    mid-stream on the victim (a chaos ``slow`` fault paces its rows); the
    router resubmits same-text/same-seed, the row high-water dedup splices
    the streams, and the final tokens are BITWISE identical to the
    undrained single-request reference — with the failover attributed as
    ``gateway.failover_total{reason="health_page"}``;
  * **chaos kill → detect, fail over, replace** — a replica process
    SIGKILLed mid-stream by an env-installed FaultPlan dies between row
    relays; the client stream heals via ``reason="conn_reset"`` failover
    (bitwise again), missed heartbeats mark the corpse, and the controller
    replaces it from the warm pool;
  * **hysteresis + bounds** — an oscillating load phase (small bursts and
    idle gaps) produces ZERO fleet actions; sustained idle produces
    exactly one scale_down; every decision row stays within
    [min_replicas, max_replicas];
  * **observability** — every decision is a ``fleet_action`` event and a
    ``fleet.actions_total{action=}`` counter; ``obs_report`` renders the
    ``FLEET:`` verdict line and attributes failovers by reason;
  * **graftlens telemetry plane** — a request served on a remote replica,
    SIGKILLed mid-stream and failed over to a second process yields ONE
    ``obs_report --request`` timeline holding spans from all three
    processes (gateway thread → dead victim → failover target) in causal
    order under a single trace_id — the victim's half read from its
    atomically-exported telemetry dir, the rest over the ``telemetry``
    RPC verb, clocks joined by the heartbeat offset estimator. The
    gateway's ``/metrics`` serves the fleet-aggregated counters, the
    native TTFT histogram (quantiles rendered from buckets by
    ``obs_report``), ``{replica=}``-labeled gauges, and the per-tenant
    usage counters backed by the append-only metering ledger.

Artifacts (smoke.json, decisions.json, metrics.jsonl, fleet_spans.jsonl,
flight/, telemetry_artifacts/ with the merged cross-process spans,
usage.jsonl, replica logs + per-replica flight bundles) land in
``--outdir`` — the dir ci.yml uploads.
Run: JAX_PLATFORMS=cpu python scripts/fleet_smoke.py
"""

import argparse
import glob
import json
import os
import subprocess
import sys
import threading
import time
import types

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import serve_replica as sr  # noqa: E402


def _post(address, payload, timeout=180.0, path="/v1/generate"):
    import http.client
    host, port = address.split("//")[1].rsplit(":", 1)
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
    conn.request("POST", path, json.dumps(payload),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    body = json.loads(resp.read() or b"{}")
    conn.close()
    return resp.status, body


def _burst(address, texts, seeds, n):
    """n concurrent blocking posts; returns (results by index, wall_s).
    results[i] = (status, body)."""
    out = {}

    def client(i):
        out[i] = _post(address, {"text": texts[i % len(texts)].tolist(),
                                 "seed": int(seeds[i])})
    t0 = time.perf_counter()
    threads = [threading.Thread(target=client, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return out, time.perf_counter() - t0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--outdir", type=str, default="fleet_artifacts")
    ap.add_argument("--slots", type=int, default=2)
    ap.add_argument("--queue_maxsize", type=int, default=6)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    os.makedirs(args.outdir, exist_ok=True)

    import jax
    import numpy as np

    from dalle_tpu import obs
    from dalle_tpu.obs import lockorder, wiretap

    # graftsync runtime half: every dalle_tpu lock created from here on is
    # instrumented; the end of the smoke asserts the acquisition order this
    # real run exhibited is acyclic and within the static golden
    lockorder.install()
    # graftwire runtime half: every frame this process sends/receives is
    # recorded; the end of the smoke asserts observed ⊆ contracts/wire.json
    wiretap.install()
    from dalle_tpu.chaos.faults import Fault, FaultPlan
    from dalle_tpu.config import DalleConfig
    from dalle_tpu.fleet import FleetController, FleetManager
    from dalle_tpu.gateway import (AdmissionController, Gateway,
                                   ReplicaRouter, SloEstimator, TenantQuotas,
                                   save_engine_aot)
    from dalle_tpu.models.dalle import DALLE, init_dalle

    obs.configure()
    flight_dir = os.path.join(args.outdir, "flight")
    obs.configure_recorder(flight_dir, min_dump_interval_s=0.0,
                           sample_interval_s=0.5)
    # graftlens: one collector joins every replica process's telemetry —
    # RPC fetch while alive, the atomic export dir after a SIGKILL — and
    # backs the gateway's fleet-aggregated /metrics
    coll = obs.TelemetryCollector()
    tel_dir = os.path.join(args.outdir, "telemetry")
    usage_log = os.path.join(args.outdir, "usage.jsonl")
    failures = []

    def check(ok, msg):
        print(("PASS " if ok else "FAIL ") + msg, flush=True)
        if not ok:
            failures.append(msg)

    # -- references + AOT export (the parent pays every compile) ----------
    cfg = DalleConfig(**sr.TINY_CFG)
    model, params = init_dalle(cfg, jax.random.PRNGKey(0), batch=2)
    rng = np.random.RandomState(args.seed)
    texts = [rng.randint(1, 20, (cfg.text_seq_len,)).astype(np.int32)
             for _ in range(4)]
    ref = {}                       # (text_idx, seed) -> token list

    def ref_for(ti, seed):
        if (ti, seed) not in ref:
            ref[(ti, seed)] = np.asarray(model.apply(
                params, np.asarray(texts[ti][None]),
                jax.random.PRNGKey(seed),
                method=DALLE.generate_images_tokens)[0]).tolist()
        return ref[(ti, seed)]

    eng_args = types.SimpleNamespace(
        untrained=True, dalle_path=None, model_seed=0,
        precision="float32", slots=args.slots, steps_per_sync=4,
        queue_maxsize=args.queue_maxsize, prefill_chunk=0,
        decode_health=False,
        # dense engine (graftpage knobs off): build_engine reads these
        # unconditionally, matching serve_replica's CLI defaults
        kv_block_tokens=0, kv_pool_blocks=None, radix_cache=True)
    aot_dir = os.path.join(args.outdir, "aot")
    manifest = save_engine_aot(sr.build_engine(eng_args), aot_dir)
    check(all(v > 0 for v in manifest["payload_bytes"].values()),
          "AOT export serialized the engine programs for the fleet")

    # -- fleet: 1 serving replica + 1 warm, controller over both ----------
    argv_base = [
        sys.executable, os.path.join(os.path.dirname(__file__),
                                     "serve_replica.py"),
        "--untrained", "--model_seed", "0", "--precision", "float32",
        "--slots", str(args.slots), "--steps_per_sync", "4",
        "--queue_maxsize", str(args.queue_maxsize),
        "--aot_dir", aot_dir, "--warmup", "--no_compile_cache",
        "--flight_dir", os.path.join(args.outdir, "replica_flight")]
    manager = FleetManager(argv_base, warm_pool=1,
                           env={"JAX_PLATFORMS": "cpu"},
                           log_dir=os.path.join(args.outdir, "replica_logs"),
                           telemetry_dir=tel_dir, collector=coll)
    try:
        rp0 = manager.spawn()
        check(rp0.handshake.get("aot_loaded") is True,
              "initial replica process loaded the AOT bundle "
              "(fingerprint matched across processes)")
        manager.prewarm()
        check(manager.warm_available == 1, "warm pool prespawned 1 replica")

        router = ReplicaRouter([rp0.remote])
        admission = AdmissionController(
            TenantQuotas(rate_per_s=1000.0, burst=1000.0),
            SloEstimator(parallelism=args.slots))
        # short windows so the smoke's burn decays in seconds, and a 1.5×
        # threshold (error rate ≥ 15% of a 0.9 objective's budget) so the
        # overload verdict is structural — the reject count of a fixed
        # burst varies with box speed, the breach must not
        sentry = obs.BurnRateSentry(
            objective=0.9, windows=((3.0, 1.5), (10.0, 1.5)),
            on_breach=lambda v: obs.dump_recorder(
                "slo_breach", extra={"dominating": v["dominating"]}))
        gw = Gateway(router, admission, slo_sentry=sentry,
                     collector=coll, usage_log=usage_log).start()
        # down_sustain deliberately dwarfs up_sustain (add capacity fast,
        # remove it slowly): the oscillating-load phase's idle gaps must
        # never accumulate into a shrink
        ctl = FleetController(
            router, manager, sentry=sentry, estimator=admission.slo,
            min_replicas=1, max_replicas=3, up_sustain=2, down_sustain=12,
            cooldown_ticks=3, retire_grace_ticks=1,
            slots_per_replica=args.slots)
        ctl.adopt(rp0)                  # the boot replica is already routed

        # -- phase A: overload burst → burn → warm scale-up ---------------
        warm_rp = manager._warm[0]
        warm_compiles_0 = warm_rp.handshake["backend_compiles"]
        n0 = 24
        results0 = {}
        wall0 = [0.0]

        def run_burst0():
            results0.update(_burst(gw.address, texts,
                                   [1000 + i for i in range(n0)], n0)[0])
        b0 = threading.Thread(target=run_burst0)
        t0 = time.perf_counter()
        b0.start()
        time.sleep(0.7)            # rejects land instantly; burn is live NOW
        a1 = ctl.tick()
        a2 = ctl.tick()
        scale_ups = [d for d in a1 + a2 if d["action"] == "scale_up"]
        b0.join()
        wall0[0] = time.perf_counter() - t0
        ok0 = [i for i, (st, _) in results0.items() if st == 200]
        rej0 = [i for i, (st, b) in results0.items()
                if st == 429 and b.get("error") == "queue_full"]
        check(len(rej0) > 0 and len(ok0) + len(rej0) == n0,
              f"overload burst: {len(ok0)}/{n0} served, {len(rej0)} "
              "queue_full rejects burned the error budget")
        check(len(scale_ups) == 1 and scale_ups[0]["reason"] == "slo_burn",
              "controller scaled up on sustained multi-window burn "
              f"(actions: {[d['action'] for d in a1 + a2]})")
        check(len(router.replicas) == 2,
              "warm replica attached — fleet is 2")
        check(all(results0[i][1]["tokens"] == ref_for(i % len(texts),
                                                      1000 + i)
                  for i in ok0),
              "every served burst request token-exact vs single-request "
              "reference")

        n1 = 14
        results1, wall1 = _burst(gw.address, texts,
                                 [2000 + i for i in range(n1)], n1)
        ok1 = [i for i, (st, _) in results1.items() if st == 200]
        check(len(ok1) == n1,
              f"post-scale-up burst: goodput recovered to {len(ok1)}/{n1} "
              f"(was {len(ok0)}/{n0}); completed req/s "
              f"{len(ok0) / wall0[0]:.2f} → {len(ok1) / wall1:.2f}")
        check(all(results1[i][1]["tokens"] == ref_for(i % len(texts),
                                                      2000 + i)
                  for i in ok1),
              "post-scale-up tokens bitwise-exact across both replicas")
        warm_h = warm_rp.remote.health()
        check(warm_h.get("backend_compiles") == warm_compiles_0,
              f"warm AOT replica served with ZERO new backend compiles "
              f"({warm_compiles_0} at handshake, "
              f"{warm_h.get('backend_compiles')} after serving)")

        # -- oscillating load: hysteresis must hold the fleet still ------
        deadline = time.time() + 20.0
        while sentry.evaluate()["burning"] and time.time() < deadline:
            time.sleep(0.5)
        check(not sentry.evaluate()["burning"],
              "burn cleared after capacity caught up")
        before = len(ctl.decisions)
        for i in range(3):
            _burst(gw.address, texts, [3000 + 10 * i, 3001 + 10 * i], 2)
            ctl.tick()
            time.sleep(0.3)
            ctl.tick()
        check(len(ctl.decisions) == before,
              "oscillating load phase: zero fleet actions (hysteresis + "
              "cooldown hold)")

        # -- phase B: mid-stream health-page drain, bitwise-invisible -----
        # engine-step chaos (serve/engine.py chaos hook; steps advance 4
        # per dispatch at steps_per_sync=4, and the --warmup request
        # consumes 1): the slow fault paces the dispatches after row 0 by
        # 0.6 s each, holding the stream open long enough for the drain
        # tick to land mid-decode
        slow_plan = FaultPlan([Fault(kind="slow", step=3, duration_s=0.6,
                                     span_steps=16)])
        sv = manager.spawn(extra_env=slow_plan.env())
        ctl.attach(sv)
        # steer the stream onto the victim: it is briefly the only routed
        # replica (membership is dynamic; the standbys come right back)
        standbys = [rp0.remote, warm_rp.remote]
        for r in standbys:
            router.remove_replica(r)
        routed = router.submit(texts[0], 5000)
        for r in standbys:
            router.add_replica(r)
        check(routed.replica_id == sv.replica_id,
              "drain-phase stream landed on the victim replica")
        rows, done_box = [], [None]
        first_row = threading.Event()

        def consume():
            for kind, payload in routed.events(timeout=30.0):
                if kind == "row":
                    rows.append(payload)
                    first_row.set()
                elif kind == "done":
                    done_box[0] = payload
            first_row.set()
        ct = threading.Thread(target=consume)
        ct.start()
        check(first_row.wait(timeout=60.0) and done_box[0] is None,
              "victim is streaming (chaos slow fault pacing its rows)")
        ctl.request_drain(sv.replica_id, reason="health_page")
        drain_acts = ctl.tick()
        ct.join(timeout=120.0)
        done = done_box[0]
        check(any(d["action"] == "drain" and d["reason"] == "health_page"
                  for d in drain_acts),
              "controller executed the health-page drain")
        check(done is not None and done["failovers"] == 1
              and done["tokens"] == ref_for(0, 5000),
              "mid-stream drain: spliced stream bitwise-identical to the "
              "undrained reference")
        check(sorted(p["row"] for p in rows)
              == list(range(cfg.image_fmap_size)),
              "every grid row delivered exactly once across the hand-off")
        ctl.tick()                                    # reap the drained victim
        time.sleep(0.2)
        check(not sv.alive, "drained victim process was killed after grace")
        snap = obs.metrics_snapshot()
        check(snap.get('gateway.failover_total{reason="health_page"}',
                       0) >= 1,
              "failover attributed as {reason=health_page}")

        # -- phase C: chaos-killed replica → conn_reset failover + replace
        # SIGKILL at engine step 9 = after the second row's dispatch: the
        # process dies BETWEEN row relays, mid-stream by construction
        kill_plan = FaultPlan([Fault(kind="kill", step=9,
                                     signal="SIGKILL")])
        kv = manager.spawn(extra_env=kill_plan.env())
        ctl.attach(kv)
        for r in standbys:
            router.remove_replica(r)
        routed = router.submit(texts[1], 6000)
        for r in standbys:
            router.add_replica(r)
        check(routed.replica_id == kv.replica_id,
              "kill-phase stream landed on the chaos victim")
        krows, kdone = [], [None]

        def kconsume():
            for kind, payload in routed.events(timeout=30.0):
                if kind == "row":
                    krows.append(payload)
                elif kind == "done":
                    kdone[0] = payload
        kt = threading.Thread(target=kconsume)
        kt.start()
        kt.join(timeout=180.0)
        check(kdone[0] is not None and kdone[0]["failovers"] == 1
              and kdone[0]["tokens"] == ref_for(1, 6000),
              "SIGKILLed replica: stream failed over bitwise-exact")
        check([d["row"] for d in krows] == list(range(cfg.image_fmap_size)),
              "rows exactly once, in order, across the process death")
        snap = obs.metrics_snapshot()
        check(snap.get('gateway.failover_total{reason="conn_reset"}',
                       0) >= 1,
              "failover attributed as {reason=conn_reset}")
        replace_deadline = time.time() + 120.0
        replaced = []
        while time.time() < replace_deadline and not replaced:
            replaced = [d for d in ctl.tick() if d["action"] == "replace"]
            time.sleep(0.3)
        check(bool(replaced),
              "controller detected the dead process (missed heartbeats) "
              "and replaced it")
        check(len(router.replicas) == 3 and not kv.alive,
              "fleet healed back to 3 with the corpse reaped")
        st, body = _post(gw.address, {"text": texts[2].tolist(),
                                      "seed": 7000})
        check(st == 200 and body["tokens"] == ref_for(2, 7000),
              "healed fleet serves token-exact")

        # -- phase D (wedge_drain): wedged-engine self-detection ----------
        # a chaos `wedge` fault hangs the victim INSIDE its engine loop
        # mid-stream: the process stays alive, answers health dials, keeps
        # heartbeating — the PR 12 gap where only an operator request_drain
        # could save the stream. Now the in-process WedgeWatchdog
        # (--wedge_timeout_s; armed safely here: AOT+warmup replicas pay
        # no compiles) sees busy-with-frozen-iteration-counter and
        # self-reports unhealthy{reason=wedged} through the health verb;
        # the controller migrate-drains it with NO operator page, the
        # router resubmits same-seed, and the splice is bitwise.
        wedge_plan = FaultPlan([Fault(kind="wedge", step=9,
                                      duration_s=600.0)])
        wm = FleetManager(argv_base + ["--wedge_timeout_s", "1.5"],
                          env={"JAX_PLATFORMS": "cpu"},
                          log_dir=os.path.join(args.outdir,
                                               "replica_logs"))
        try:
            # explicit id: the second manager's replica-N sequence would
            # collide with the main fleet's ids and clobber the
            # controller's supervision table
            wv = wm.spawn(replica_id="wedge-0",
                          extra_env=wedge_plan.env())
            ctl.attach(wv)
            # steer onto the victim: every OTHER routed replica (the
            # originals plus phase C's replacement) steps out briefly —
            # re-added in a finally so a failed submit can't strand the
            # rest of the smoke on a one-replica router
            others = [r for r in router.replicas
                      if r.replica_id != wv.replica_id]
            for r in others:
                router.remove_replica(r)
            try:
                routed = router.submit(texts[2], 9000)
            finally:
                for r in others:
                    router.add_replica(r)
            check(routed.replica_id == wv.replica_id,
                  "wedge-phase stream landed on the chaos victim")
            wrows, wdone = [], [None]

            def wconsume():
                for kind, payload in routed.events(timeout=30.0):
                    if kind == "row":
                        wrows.append(payload)
                    elif kind == "done":
                        wdone[0] = payload
            wt = threading.Thread(target=wconsume)
            wt.start()
            wedged_seen = False
            deadline = time.time() + 30.0
            while time.time() < deadline:
                h = wv.remote.health()
                if h.get("wedged") and not h.get("healthy", True):
                    wedged_seen = True
                    break
                time.sleep(0.25)
            check(wedged_seen,
                  "wedged replica SELF-reported unhealthy{reason=wedged} "
                  "through the health verb (live process, stuck engine)")
            wedge_drains = []
            deadline = time.time() + 20.0
            while time.time() < deadline and not wedge_drains:
                wedge_drains = [d for d in ctl.tick()
                                if d["action"] == "drain"
                                and d["reason"] == "wedged"]
                time.sleep(0.2)
            check(bool(wedge_drains),
                  "controller drained the wedged replica with NO operator "
                  "request_drain")
            wt.join(timeout=120.0)
            check(wdone[0] is not None and wdone[0]["failovers"] == 1
                  and wdone[0]["tokens"] == ref_for(2, 9000),
                  "wedge drain: in-flight stream spliced bitwise-identical "
                  "to the undisturbed reference")
            check(sorted(p["row"] for p in wrows)
                  == list(range(cfg.image_fmap_size)),
                  "every grid row delivered exactly once across the wedge "
                  "hand-off")
            ctl.tick()                     # reap the drained victim
            time.sleep(0.2)
            ctl.tick()
            check(not wv.alive,
                  "wedged victim process was killed after grace")
            snap = obs.metrics_snapshot()
            check(snap.get('gateway.failover_total{reason="wedged"}',
                           0) >= 1,
                  "failover attributed as {reason=wedged}")
            check(snap.get('degrade.actions_total{reason="wedged"}',
                           0) >= 1,
                  "degrade.actions_total{reason=wedged} recorded the "
                  "response")
        finally:
            wm.shutdown()

        # replica-SIDE postmortem (graftlens satellite): the wedge trips
        # inside the victim process, whose --flight_dir subtree lives in
        # the artifact dir — a bundle from the replica's own recorder must
        # have landed there (the gateway-side bundles above can never hold
        # the stuck process's final state)
        replica_bundles = sorted(glob.glob(os.path.join(
            args.outdir, "replica_flight", "*", "postmortem_*")))
        check(bool(replica_bundles),
              f"wedged replica dumped its own flight bundle into the "
              f"artifact dir ({len(replica_bundles)} replica-side "
              f"bundle(s))")

        # -- cross-process AOT fingerprint refusal: a replica handed a
        # bundle built under a mismatched config must refuse LOUDLY in its
        # handshake and serve on the jit fallback (cold, correct)
        mm_argv = list(argv_base)
        mm_argv[mm_argv.index("--slots") + 1] = str(args.slots + 1)
        mm_argv.remove("--warmup")        # jit fallback: nothing to prewarm
        mm = FleetManager(mm_argv, env={"JAX_PLATFORMS": "cpu"},
                          log_dir=os.path.join(args.outdir, "replica_logs"))
        try:
            mmr = mm.spawn(replica_id="mismatch-0")
            check(mmr.handshake["aot_loaded"] is False
                  and "slots" in (mmr.handshake["aot_refusal"] or ""),
                  "mismatched AOT bundle refused loudly in the handshake "
                  f"({mmr.handshake['aot_refusal']})")
            mstream = mmr.remote.submit(texts[3], 8000)
            mdone = None
            for kind, payload in mstream.events(
                    timeout=300.0, still_alive=lambda: mmr.remote.healthy):
                if kind == "done":
                    mdone = payload
            check(mdone is not None and mdone.tokens == ref_for(3, 8000),
                  "refusing replica still serves token-exact on the jit "
                  "fallback")
        finally:
            mm.shutdown()

        # -- sustained idle → one bounded scale_down, then hysteresis -----
        downs = []
        for _ in range(40):
            downs += [d for d in ctl.tick() if d["action"] == "scale_down"]
            if downs:
                break
            time.sleep(0.05)
        check(len(downs) == 1 and downs[0]["fleet"] >= ctl.min_replicas,
              "sustained idle produced a bounded scale_down")
        # a fresh shrink needs down_sustain MORE idle ticks — the next few
        # ticks cannot fire a second one (no collapse, deterministically)
        post = []
        for _ in range(8):
            post += [d for d in ctl.tick() if d["action"] == "scale_down"]
        check(post == [],
              "no second scale_down inside the hysteresis window")
        check(all(ctl.min_replicas <= d["fleet"] <= ctl.max_replicas
                  for d in ctl.decisions),
              "every decision row within [min_replicas, max_replicas]")

        # -- observability: decision log, metrics, FLEET verdict ----------
        ctl.tick()
        snap = obs.metrics_snapshot()
        actions = {k: v for k, v in snap.items()
                   if k.startswith("fleet.actions_total")}
        check(len(actions) >= 3 and "fleet.size" in snap,
              f"fleet_action counters + size gauge live ({actions})")
        with open(os.path.join(args.outdir, "decisions.json"), "w") as fh:
            json.dump(ctl.decisions, fh, indent=2)
        with open(os.path.join(args.outdir, "metrics.jsonl"), "w") as fh:
            fh.write(json.dumps({"step": 0, **snap}) + "\n")
        n_spans = obs.export_spans_jsonl(
            os.path.join(args.outdir, "fleet_spans.jsonl"))
        rep = subprocess.run(
            [sys.executable, os.path.join(os.path.dirname(__file__),
                                          "obs_report.py"), args.outdir],
            capture_output=True, text=True,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        check(rep.returncode == 0 and "FLEET:" in rep.stdout,
              "obs_report prints the FLEET verdict line")
        check("by reason" in rep.stdout and "conn_reset" in rep.stdout,
              "obs_report attributes failovers by reason")
        check("DEGRADE:" in rep.stdout and "wedged" in rep.stdout,
              "obs_report renders the DEGRADE verdict naming the wedged "
              "response")

        # -- phase E (graftlens): ONE timeline across three processes -----
        # a fresh victim, paced by a slow fault so its telemetry exporter
        # flushes mid-stream, then SIGKILLed between row relays: the
        # request fails over to a second replica process, and the
        # collector must join gateway thread + dead victim + failover
        # target into a single --request timeline, while the gateway's
        # /metrics serves the fleet-aggregated counters and histograms
        tel_plan = FaultPlan([Fault(kind="slow", step=3, duration_s=0.4,
                                    span_steps=8),
                              Fault(kind="kill", step=9,
                                    signal="SIGKILL")])
        tm = FleetManager(argv_base + ["--telemetry_interval_s", "0.05"],
                          env={"JAX_PLATFORMS": "cpu"},
                          log_dir=os.path.join(args.outdir, "replica_logs"),
                          telemetry_dir=tel_dir, collector=coll)
        try:
            tv = tm.spawn(replica_id="lens-victim",
                          extra_env=tel_plan.env())
            router.add_replica(tv.remote)
            others = [r for r in router.replicas
                      if r.replica_id != tv.replica_id]
            for r in others:
                router.remove_replica(r)
            post_box = {}

            def tel_post():
                st, body = _post(gw.address, {"text": texts[3].tolist(),
                                              "seed": 9500,
                                              "tenant": "lens"})
                post_box["status"], post_box["body"] = st, body

            pt = threading.Thread(target=tel_post)
            pt.start()
            time.sleep(0.5)        # routed (instantly) onto the victim;
            for r in others:       # bring the failover targets back in
                router.add_replica(r)
            pt.join(timeout=180.0)
            body = post_box.get("body") or {}
            tel_tid = body.get("trace_id")
            check(post_box.get("status") == 200
                  and body.get("failovers") == 1
                  and body.get("replica") != tv.replica_id
                  and body.get("tokens") == ref_for(3, 9500),
                  "telemetry-phase request: served on the victim, "
                  "SIGKILLed mid-stream, failed over bitwise-exact")
            time.sleep(0.3)        # the target's engine-loop spans land
            coll.poll()
            tel_art = os.path.join(args.outdir, "telemetry_artifacts")
            n_merged = coll.export_merged_jsonl(
                os.path.join(tel_art, "merged_spans.jsonl"))
            fleet_snap = coll.fleet_metrics()
            with open(os.path.join(tel_art, "metrics.jsonl"), "w") as fh:
                fh.write(json.dumps({"step": 0, **fleet_snap}) + "\n")
            with open(os.path.join(tel_art,
                                   "merged_spans.jsonl")) as fh:
                merged = [json.loads(line) for line in fh]
            tid_procs = {r.get("proc") for r in merged
                         if (r.get("args") or {}).get("trace_id")
                         == tel_tid}
            check({"gateway", tv.replica_id,
                   body.get("replica")} <= tid_procs,
                  f"merged spans carry the trace across gateway + victim "
                  f"+ failover target ({sorted(tid_procs)}; {n_merged} "
                  f"spans merged)")

            # the REAL CLI over the merged export: one wall-clock-ordered
            # timeline spanning all three processes, victim before target
            rep3 = subprocess.run(
                [sys.executable, os.path.join(os.path.dirname(__file__),
                                              "obs_report.py"),
                 tel_art, "--request", tel_tid],
                capture_output=True, text=True,
                env=dict(os.environ, JAX_PLATFORMS="cpu"))
            out3 = rep3.stdout
            check(rep3.returncode == 0 and "in 3 process(es)" in out3,
                  "obs_report --request joins ONE timeline across 3 "
                  "processes")
            vpos = out3.find(tv.replica_id)
            fpos = out3.find(str(body.get("replica")))
            check(0 <= vpos < fpos
                  and out3.count("serve/request_queue_wait") == 2,
                  "causal order: the dead victim's spans precede the "
                  "failover target's (two admissions, one identity)")

            # fleet-aggregated /metrics over the real socket: the gateway
            # process runs no engine, so serve.* series can only have come
            # from replica processes via the collector
            import http.client
            host, port = gw.address.split("//")[1].rsplit(":", 1)
            mc = http.client.HTTPConnection(host, int(port), timeout=30)
            mc.request("GET", "/metrics")
            mtext = mc.getresponse().read().decode()
            mc.close()
            check("dalle_serve_requests_completed_total" in mtext
                  and 'dalle_serve_ttft_seconds_bucket{le="' in mtext
                  and "# TYPE dalle_serve_ttft_seconds histogram" in mtext,
                  "gateway /metrics serves fleet-aggregated remote "
                  "counters + the native TTFT histogram")
            check('{replica="' in mtext
                  and "dalle_fleet_telemetry_sources" in mtext,
                  "remote gauges labeled {replica=} under the source-count "
                  "gauge")

            # obs_report over the fleet snapshot: TTFT quantiles computed
            # from the merged cumulative buckets, never raw samples
            rep4 = subprocess.run(
                [sys.executable, os.path.join(os.path.dirname(__file__),
                                              "obs_report.py"), tel_art],
                capture_output=True, text=True,
                env=dict(os.environ, JAX_PLATFORMS="cpu"))
            check("latency histograms" in rep4.stdout
                  and "serve.ttft_seconds" in rep4.stdout
                  and "p50=" in rep4.stdout and "p95=" in rep4.stdout,
                  "obs_report renders fleet TTFT p50/p95 from merged "
                  "buckets")
            check("TELEMETRY:" in rep4.stdout,
                  "obs_report prints the TELEMETRY plane verdict")
            check("USAGE: metered" in rep4.stdout
                  and "lens" in rep4.stdout,
                  "obs_report renders the per-tenant usage table")
            with open(usage_log) as fh:
                ledger = [json.loads(line) for line in fh]
            check(any(r.get("tenant") == "lens" and r.get("tokens_out")
                      for r in ledger),
                  f"usage ledger metered the request ({len(ledger)} "
                  f"ledger lines)")
        finally:
            tm.shutdown()

        # graftsync cross-check: the lock-acquisition order this real
        # multi-threaded run exhibited must be acyclic and a subgraph of
        # the static golden (contracts/sync.json)
        from dalle_tpu.analysis.sync_flow import build_repo_model
        obs_edges = lockorder.observed_edges()
        check(not lockorder.cycles(),
              f"observed lock-acquisition graph acyclic "
              f"({len(obs_edges)} edges over "
              f"{len(lockorder.observed_sites())} locks)")
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        site_to_id = build_repo_model(root).lock_by_site()
        with open(os.path.join(root, "contracts", "sync.json")) as fh:
            golden_edges = {(d["src"], d["dst"])
                            for d in json.load(fh)["edges"]}
        unknown = [lockorder.format_edge(e) for e in obs_edges
                   if e.src not in site_to_id or e.dst not in site_to_id]
        mapped = {(site_to_id[e.src], site_to_id[e.dst]) for e in obs_edges
                  if e.src in site_to_id and e.dst in site_to_id}
        extra = sorted(f"{s} -> {d}" for s, d in mapped - golden_edges)
        check(not unknown and not extra,
              "observed lock graph ⊆ static golden (unknown locks: "
              f"{unknown or 'none'}; edges beyond golden: "
              f"{extra or 'none'})")

        # graftwire cross-check: every frame this gateway-side process put
        # on (or took off) the wire must fit a sender schema of the golden
        # protocol contract, and the declared lifecycle machines it pins
        # must be acyclic
        from dalle_tpu.analysis.wire_flow import lifecycle_cycles
        with open(os.path.join(root, "contracts", "wire.json")) as fh:
            wire_golden = json.load(fh)
        frames = wiretap.observed()
        violations = [str(v) for v in wiretap.conformance(wire_golden)]
        check(frames and not violations,
              f"observed wire frames ⊆ static golden ({len(frames)} "
              f"distinct frame shapes; violations: {violations or 'none'})")
        cyc = lifecycle_cycles(
            {n: {"edges": [tuple(e) for e in m["edges"]]}
             for n, m in wire_golden["lifecycles"].items()})
        check(not cyc,
              f"golden lifecycle machines acyclic ({cyc or 'no cycles'})")

        summary = {
            "burst0": {"offered": n0, "completed": len(ok0),
                       "rps": len(ok0) / wall0[0]},
            "lock_sites_observed": len(lockorder.observed_sites()),
            "lock_edges_observed": [lockorder.format_edge(e)
                                    for e in obs_edges],
            "wire_frames_observed": [
                [verb, direction, kind, sorted(fields)]
                for verb, direction, kind, fields in frames],
            "burst1": {"offered": n1, "completed": len(ok1),
                       "rps": len(ok1) / wall1},
            "warm_backend_compiles_delta":
                warm_h.get("backend_compiles") - warm_compiles_0,
            "decisions": [d["action"] for d in ctl.decisions],
            "failover_reasons": {
                k: v for k, v in snap.items()
                if k.startswith("gateway.failover_total")},
            "degrade": {k: v for k, v in snap.items()
                        if k.startswith("degrade.")},
            "flight_bundles": sorted(os.path.basename(p) for p in glob.glob(
                os.path.join(flight_dir, "postmortem_*"))),
            "replica_bundles": sorted(
                os.path.relpath(p, args.outdir) for p in glob.glob(
                    os.path.join(args.outdir, "replica_flight", "*",
                                 "postmortem_*"))),
            "telemetry": {"merged_spans": n_merged,
                          "trace_procs": sorted(tid_procs),
                          "sources": coll.sources()},
            "spans_exported": n_spans,
            "failures": failures,
        }
        with open(os.path.join(args.outdir, "smoke.json"), "w") as fh:
            json.dump(summary, fh, indent=2)
        print(json.dumps({"metric": "fleet_smoke", **summary}), flush=True)
        gw.shutdown(drain=True, timeout=60)
    finally:
        manager.shutdown()
        obs.disable_recorder()
        obs.disable()
    if failures:
        print(f"fleet_smoke: FAILED ({len(failures)} checks)")
        return 1
    print("fleet_smoke: GREEN")
    return 0


if __name__ == "__main__":
    sys.exit(main())
