#!/usr/bin/env python
"""Recon-quality delta of the shipped tiny perceptual net vs the ones-init
fallback (VERDICT r2 next #2 'Done =' criterion).

Trains two identical small VQGANs on the synthetic shapes corpus — one with
the in-repo-trained tiny perceptual weights (perceptual_net='tiny', the
default), one with the offline ones-init fallback ('vgg' with no vgg.pth) —
and reports held-out reconstruction metrics: L1, PSNR, and Sobel-edge L1
(edge fidelity is where a real perceptual term shows; plain L1 slightly
favors whichever run weights the pixel term most).

Usage: python scripts/eval_perceptual_delta.py [steps]
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np


def sobel_edges(img):
    """|∇| magnitude per channel, valid region (N, H-2, W-2, C)."""
    gx = (img[:, :-2, 2:] - img[:, :-2, :-2] +
          2 * (img[:, 1:-1, 2:] - img[:, 1:-1, :-2]) +
          img[:, 2:, 2:] - img[:, 2:, :-2])
    gy = (img[:, 2:, :-2] - img[:, :-2, :-2] +
          2 * (img[:, 2:, 1:-1] - img[:, :-2, 1:-1]) +
          img[:, 2:, 2:] - img[:, :-2, 2:])
    return np.sqrt(gx ** 2 + gy ** 2)


def run_arm(name, perceptual_net, train_imgs, test_imgs, steps, batch,
            perceptual_weight=1.0):
    from dalle_tpu.config import MeshConfig, OptimConfig, TrainConfig, VQGANConfig
    from dalle_tpu.models.gan import GANLossConfig
    from dalle_tpu.train.trainer_vqgan import VQGANTrainer

    cfg = VQGANConfig(embed_dim=32, n_embed=256, z_channels=32, resolution=64,
                      ch=32, ch_mult=(1, 2, 2), num_res_blocks=1,
                      attn_resolutions=())
    tc = TrainConfig(batch_size=batch, checkpoint_dir=f"/tmp/pdelta_{name}",
                     preflight_checkpoint=False, mesh=MeshConfig(dp=1),
                     metrics_every=100, seed=0,
                     optim=OptimConfig(learning_rate=2e-4))
    # disc never activates: isolate pixel+perceptual; both arms share every
    # other knob and the same data order
    lc = GANLossConfig(disc_start=10 ** 9, perceptual_weight=perceptual_weight,
                       perceptual_net=perceptual_net)
    tr = VQGANTrainer(cfg, tc, loss_cfg=lc)
    rng = np.random.RandomState(0)
    n = len(train_imgs)
    for s in range(steps):
        idx = rng.randint(0, n, batch)
        tr.train_step(train_imgs[idx])

    # held-out recon (trainer API — handles gan/nodisc param layouts)
    rec = np.asarray(jax.device_get(tr.reconstruct(test_imgs)))
    l1 = float(np.mean(np.abs(rec - test_imgs)))
    mse = float(np.mean((rec - test_imgs) ** 2))
    psnr = float(10 * np.log10(4.0 / mse))          # [-1,1] range → peak 2
    edge_l1 = float(np.mean(np.abs(sobel_edges(rec) - sobel_edges(test_imgs))))
    out = {"arm": name, "perceptual_net": perceptual_net, "steps": steps,
           "l1": round(l1, 5), "psnr_db": round(psnr, 3),
           "edge_l1": round(edge_l1, 5)}
    print(json.dumps(out), flush=True)
    return out


def main():
    steps = int(sys.argv[1]) if len(sys.argv) > 1 else 300
    from dalle_tpu.data.synthetic import ShapesDataset

    ds = ShapesDataset(image_size=64, variants=6, seed=0)
    imgs = np.stack([ds[i].image for i in range(len(ds))])
    imgs = imgs.astype(np.float32) / 127.5 - 1.0     # [-1, 1]
    rng = np.random.RandomState(42)
    perm = rng.permutation(len(imgs))
    test, train = imgs[perm[:32]], imgs[perm[32:]]

    a = run_arm("tiny", "tiny", train, test, steps, batch=16)
    b = run_arm("onesinit", "vgg", train, test, steps, batch=16)
    # scale-matched arm: the tiny metric's magnitude is ~4.5x the ones-init
    # random-feature metric on the same distortions (it matches real-LPIPS
    # ranges; ones-init is the weak one), so weight 1.0 vs 1.0 compares
    # different effective perceptual strengths. 1/4.5 matches them.
    c = run_arm("tiny_matched", "tiny", train, test, steps, batch=16,
                perceptual_weight=0.22)
    print(json.dumps({
        "delta_psnr_db": round(a["psnr_db"] - b["psnr_db"], 3),
        "delta_edge_l1": round(b["edge_l1"] - a["edge_l1"], 5),
        "tiny_wins_edges": a["edge_l1"] < b["edge_l1"],
        "matched_delta_psnr_db": round(c["psnr_db"] - b["psnr_db"], 3),
        "matched_delta_edge_l1": round(b["edge_l1"] - c["edge_l1"], 5)}))


if __name__ == "__main__":
    main()
