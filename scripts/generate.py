#!/usr/bin/env python
"""Generate images from a trained DALL·E checkpoint.

Reference: legacy/generate.py — load checkpoint, rebuild the VAE by class name
(:93-100), batched ``generate_images`` with top-k filtering (:125-127), JPEG
outputs in one directory per prompt (:133-140), ``--gentxt`` caption completion
(:115-117), multiple prompts split on ``|`` (:112).

Example:
  python scripts/generate.py --dalle_path ./dalle_ckpt --untrained_vae \
      --image_size 64 --text "red circle|blue square" --num_images 2
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import (add_compile_cache_args, add_vae_args,  # noqa: E402
                     build_vae_from_args, enable_compile_cache,
                     load_model_checkpoint, load_vae_sidecar, save_image_grid)


def build_parser():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--dalle_path", type=str, required=True,
                    help="checkpoint dir from scripts/train_dalle.py")
    ap.add_argument("--text", type=str, required=True,
                    help="prompt(s), split on |")
    ap.add_argument("--num_images", type=int, default=4)
    ap.add_argument("--batch_size", type=int, default=4)
    ap.add_argument("--top_k_thres", type=float, default=0.9,
                    help="top-k fraction kept (reference generate.py:125)")
    ap.add_argument("--temperature", type=float, default=1.0)
    ap.add_argument("--cond_scale", type=float, default=1.0,
                    help="classifier-free guidance scale")
    ap.add_argument("--gentxt", action="store_true",
                    help="complete the caption with generate_texts first")
    ap.add_argument("--bf16", action="store_true",
                    help="bf16 weights + KV cache in the decode loop "
                         "(~1.6x faster on TPU; sampling stays f32)")
    ap.add_argument("--kv_int8", action="store_true",
                    help="additionally quantize the KV cache to int8 "
                         "(implies --bf16; another ~1.4x at batch 64)")
    ap.add_argument("--int8w", action="store_true",
                    help="int8 matmul weights + int8 KV for the decode loop "
                         "(per-channel scales; halves weight HBM traffic)")
    ap.add_argument("--speculative", type=int, default=0, metavar="GAMMA",
                    help="draft-and-verify decode with GAMMA drafts/round "
                         "(measured 0.366->0.281s p50 at b64/gamma=2 on a "
                         "trained model; sampling-exact; needs "
                         "cond_scale=1.0)")
    ap.add_argument("--draft", type=str, default="row",
                    choices=("row", "repeat"),
                    help="speculative draft prior: token one grid-row above "
                         "| repeat last token")
    ap.add_argument("--fast_topk", action="store_true",
                    help="approximate per-step top-k via the TPU topk unit "
                         "(exact sort is ~17%% of decode time at batch 64)")
    ap.add_argument("--clip_path", type=str, default=None,
                    help="CLIP checkpoint dir (scripts/train_clip.py): rerank "
                         "generations, best first (reference "
                         "generate_images :553-555)")
    ap.add_argument("--outputs_dir", type=str, default="./outputs")
    ap.add_argument("--trace", type=str, default=None, metavar="DIR",
                    help="grafttrace the run: per-prompt/batch spans + "
                         "per-token decode latency, exported to DIR as "
                         "Perfetto trace.json + spans.jsonl "
                         "(docs/OBSERVABILITY.md)")
    ap.add_argument("--tokenizer", type=str, default="simple")
    ap.add_argument("--bpe_path", type=str, default=None)
    ap.add_argument("--image_size", type=int, default=128)
    ap.add_argument("--seed", type=int, default=0)
    add_vae_args(ap)
    add_compile_cache_args(ap)
    from dalle_tpu.parallel import wrap_arg_parser
    return wrap_arg_parser(ap)


def load_dalle(ckpt_dir: str, backend):
    """Rebuild the exact model from checkpoint-embedded hparams (reference
    generate.py:82-106)."""
    from dalle_tpu.config import DalleConfig
    from dalle_tpu.models.dalle import init_dalle

    return load_model_checkpoint(ckpt_dir, "DALLE", DalleConfig, init_dalle)


def main(argv=None):
    args = build_parser().parse_args(argv)
    enable_compile_cache(args)
    import jax
    import numpy as np
    from dalle_tpu.models.wrapper import DalleWithVae
    from dalle_tpu.parallel import set_backend_from_args
    from dalle_tpu.text.tokenizer import get_tokenizer

    from dalle_tpu import obs
    if args.trace:
        obs.configure()
    backend = set_backend_from_args(args).initialize()
    tok_kw = {"bpe_path": args.bpe_path} if args.bpe_path else {}
    tokenizer = get_tokenizer(args.tokenizer, **tok_kw)
    model, params, meta = load_dalle(args.dalle_path, backend)
    if tokenizer.vocab_size > model.cfg.num_text_tokens:
        # mirror train_dalle's validation: larger-vocab ids would be silently
        # clipped by the embedding gather and condition on garbage
        print(f"error: tokenizer vocab {tokenizer.vocab_size} > checkpoint "
              f"num_text_tokens {model.cfg.num_text_tokens} — pass the "
              f"--tokenizer/--bpe_path the model was trained with",
              file=sys.stderr)
        return 2

    explicit_vae = (args.vae_path or args.taming or args.vqgan_model_path
                    or args.untrained_vae)
    vae = None if explicit_vae else load_vae_sidecar(args.dalle_path)
    if vae is None:
        # explicit flags, or a checkpoint without an embedded VAE (pretrained
        # wrappers rebuild from their own cache — reference generate.py:93-100)
        vae = build_vae_from_args(args, backend)
    want = meta.get("vae_class_name")
    if want and want != type(vae).__name__:
        # the reference hard-errors on class mismatch (generate.py:100)
        raise ValueError(f"checkpoint was trained with {want}, got "
                         f"{type(vae).__name__} — pass the matching vae flags")
    dv = DalleWithVae(model, params, vae)
    cfg = model.cfg
    key = jax.random.PRNGKey(args.seed)

    clip = None
    if args.clip_path:
        from dalle_tpu.config import ClipConfig
        from dalle_tpu.models.clip import init_clip
        clip_model, clip_params, _ = load_model_checkpoint(
            args.clip_path, "CLIP", ClipConfig, init_clip)
        clip = (clip_model, clip_params)

    prompts = [t.strip() for t in args.text.split("|") if t.strip()]
    for prompt in prompts:
        with obs.span("generate/prompt", prompt=prompt[:64]):
            text_str = prompt
            if args.gentxt:
                tkey, key = jax.random.split(key)
                prime = tokenizer.tokenize([prompt], cfg.text_seq_len,
                                           truncate_text=True)
                prime = prime[:, :max(1, int((prime != 0).sum()))]
                out_ids = dv.generate_texts(tkey, np.asarray(prime))
                text_str = tokenizer.decode(np.asarray(out_ids)[0])
                print(f"gentxt: {prompt!r} → {text_str!r}")
            text = tokenizer.tokenize([text_str], cfg.text_seq_len,
                                      truncate_text=True)
            outdir = os.path.join(args.outputs_dir,
                                  text_str.replace(" ", "_")[:64])
            os.makedirs(outdir, exist_ok=True)
            made = 0
            all_imgs, all_scores = [], []
            while made < args.num_images:
                n = min(args.batch_size, args.num_images - made)
                bkey, key = jax.random.split(key)
                batch_text = np.repeat(text, n, axis=0)
                out = dv.generate_images(
                    batch_text, bkey, filter_thres=args.top_k_thres,
                    temperature=args.temperature, cond_scale=args.cond_scale,
                    clip=clip,
                    precision=("int8w" if args.int8w
                               else "bf16_int8kv" if args.kv_int8
                               else "bfloat16" if args.bf16 else "float32"),
                    topk_approx=args.fast_topk,
                    speculative=args.speculative, draft=args.draft)
                if clip is not None:
                    # reranking needs the whole set — accumulate
                    imgs, scores = out
                    all_scores.append(np.asarray(scores))
                    all_imgs.append(np.asarray(imgs))
                else:
                    # stream each batch to disk as it is produced
                    save_image_grid(np.asarray(out),
                                    os.path.join(outdir, f"img_{made}_{{}}.png"))
                made += n
            if clip is not None:
                # best-first ordering by CLIP similarity (reference :553-555)
                imgs = np.concatenate(all_imgs)
                scores = np.concatenate(all_scores)
                order = np.argsort(-scores)
                print("clip scores (best first): "
                      + " ".join(f"{scores[i]:.4f}" for i in order))
                save_image_grid(imgs[order], os.path.join(outdir, "img_{}.png"))
            print(f"wrote {made} images for {text_str!r} → {outdir}")
    if args.trace:
        os.makedirs(args.trace, exist_ok=True)
        n = obs.export_chrome_trace(os.path.join(args.trace, "trace.json"))
        obs.export_spans_jsonl(os.path.join(args.trace, "spans.jsonl"))
        snap = obs.metrics_snapshot()
        if "obs.decode_per_token_ms" in snap:
            print(f"[trace] last per-token decode latency: "
                  f"{snap['obs.decode_per_token_ms']:.3f} ms")
        print(f"[trace] {n} spans → {args.trace}/trace.json (Perfetto), "
              f"spans.jsonl (scripts/obs_report.py)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
