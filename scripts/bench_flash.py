#!/usr/bin/env python
"""Flash-vs-dense attention microbenchmark on the attached chip.

Times fwd+bwd through the two attention cores the Transformer can use —
the fused dense path (ops/attention.py:attend) and the Pallas flash kernel
with block skipping (ops/flash_attention.py) — across sequence lengths,
mask families, and block sizes. Records the crossover table that justifies
``use_pallas`` (VERDICT r1 #5).

Run: python scripts/bench_flash.py [--seqs 512,1024,2048,4096]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def timeit(grad_fn, q, k, v, iters=100, warmup=2):
    """Per-iteration time of fwd+bwd, measured as ONE dispatched scan of
    ``iters`` chained calls — per-call dispatch through the device tunnel is
    ~20 ms, far larger than the kernels being measured."""
    eps = jnp.asarray(1e-30, q.dtype)  # runtime value: blocks DCE/folding

    @jax.jit
    def many(q, k, v, eps):
        def body(carry, _):
            q, k, v = carry
            gq, gk, gv = grad_fn(q, k, v)
            return (q + eps * gq, k + eps * gk, v + eps * gv), ()
        (q, k, v), _ = jax.lax.scan(body, (q, k, v), None, length=iters)
        return jnp.sum(q.astype(jnp.float32))  # scalar: cheap to pull

    for _ in range(warmup):
        r = many(q, k, v, eps)
    np.asarray(jax.device_get(r))  # hard sync (tunnel-safe scalar pull)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        r = many(q, k, v, eps)
        np.asarray(jax.device_get(r))
        best = min(best, time.perf_counter() - t0)
    return best / iters


def masks_for(kind, n, text_len, fmap):
    """(numpy mask, structured spec) per kind."""
    if kind == "full":
        return None, None
    from dalle_tpu.ops.attn_masks import axial_mask, conv_like_mask
    if kind == "axial_row":
        return (np.asarray(axial_mask(text_len, fmap, axis=0)),
                ("axial", text_len, fmap, 0))
    if kind == "conv_like":
        return (np.asarray(conv_like_mask(text_len, fmap, kernel_size=5)),
                ("conv", text_len, fmap, 5, 1))
    raise ValueError(kind)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seqs", type=str, default="512,1024,2048,4096")
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--dim_head", type=int, default=64)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--blocks", type=str, default="128,256,512")
    ap.add_argument("--dtype", type=str, default="bfloat16")
    args = ap.parse_args()

    from dalle_tpu.ops.attention import attend
    from dalle_tpu.ops.flash_attention import flash_attention, sparsity_fraction

    dt = jnp.dtype(args.dtype)
    rows = []
    for n in (int(s) for s in args.seqs.split(",")):
        # DALL·E geometry: 256 text tokens + fmap² image tokens
        fmap = int(round((n - 256) ** 0.5))
        n_eff = 256 + fmap * fmap
        key = jax.random.PRNGKey(0)
        q, k, v = (jax.random.normal(jax.random.fold_in(key, i),
                                     (args.batch, args.heads, n_eff,
                                      args.dim_head), dt)
                   for i in range(3))

        for kind in ("full", "axial_row", "conv_like"):
            mask, spec = masks_for(kind, n_eff, 256, fmap)
            if mask is not None and mask.shape[0] < n_eff:
                continue

            def dense_loss(q, k, v):
                o = attend(q, k, v, causal=True, softmax_f32=False,
                           static_mask=None if mask is None
                           else jnp.asarray(mask[:n_eff, :n_eff]))
                return jnp.sum(o.astype(jnp.float32))

            dense = jax.grad(dense_loss, argnums=(0, 1, 2))
            try:
                t_dense = timeit(dense, q, k, v)
            except Exception as e:  # noqa: BLE001 - sweep point: a
                # rejected config becomes an error row, not an aborted sweep
                print(json.dumps({"seq": n_eff, "mask": kind, "dense_error":
                                  str(e)[:120]}), flush=True)
                t_dense = None

            best = None
            for blk in (int(b) for b in args.blocks.split(",")):
                if blk > n_eff:
                    continue

                def flash_loss(q, k, v, _blk=blk):
                    o = flash_attention(q, k, v, causal=True,
                                        mask=None if mask is None else
                                        mask[:n_eff, :n_eff],
                                        mask_spec=spec,
                                        block_q=_blk, block_k=_blk)
                    return jnp.sum(o.astype(jnp.float32))

                fl = jax.grad(flash_loss, argnums=(0, 1, 2))
                try:
                    t = timeit(fl, q, k, v)
                except Exception as e:  # noqa: BLE001 - sweep point
                    print(json.dumps({"seq": n_eff, "mask": kind, "block": blk,
                                      "error": str(e)[:120]}), flush=True)
                    continue
                if best is None or t < best[1]:
                    best = (blk, t)

            frac = sparsity_fraction(
                n_eff, best[0] if best else 128, best[0] if best else 128,
                mask if mask is None else mask[:n_eff, :n_eff])
            row = {"seq": n_eff, "mask": kind,
                   "dense_ms": None if t_dense is None else round(t_dense * 1e3, 3),
                   "flash_ms": None if best is None else round(best[1] * 1e3, 3),
                   "best_block": None if best is None else best[0],
                   "block_frac": round(frac, 3),
                   "speedup": None if (best is None or t_dense is None)
                   else round(t_dense / best[1], 2)}
            rows.append(row)
            print(json.dumps(row), flush=True)

    print("\n| seq | mask | dense ms | flash ms | best block | blocks visited | speedup |")
    print("|---|---|---|---|---|---|---|")
    for r in rows:
        print(f"| {r['seq']} | {r['mask']} | {r['dense_ms']} | {r['flash_ms']} "
              f"| {r['best_block']} | {r['block_frac']} | {r['speedup']}x |")


if __name__ == "__main__":
    main()
