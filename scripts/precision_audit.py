#!/usr/bin/env python
"""graftnum CLI — precision-flow audit over the registered graftir entries.

    python scripts/precision_audit.py                 # CI gate
    python scripts/precision_audit.py --entries serve_decode,serve_refill
    python scripts/precision_audit.py --report precision_artifacts
    python scripts/precision_audit.py --list-rules

Traces every registered entry point (dalle_tpu/analysis/contracts.py — no
compilation, this is the cheap half of the graftir pipeline) and runs the
forward precision-flow analysis (dalle_tpu/analysis/precision_flow.py):
low-precision accumulation in reductions, int8 matmuls without a full-width
accumulator, dequantized values consumed without their scale, dequant
scales on a contracted axis, double rounding, quantization-defeating
upcasts, orphaned scales. Findings name their ``file::function`` site and
fail the stage; a justified exception is a source waiver in the entry's
source file, graftir-style::

    # graftir: allow=precision -- <reason>

``--report DIR`` writes ``report.txt`` plus ``boundary_map.json`` — the
per-entry quantization boundary map (int8 matmul sites × accumulator
dtypes, dequant sites × scale axes, value-class histogram) that ci.yml
uploads alongside the ir_artifacts. The same boundary map is pinned as the
``precision`` section of the contract goldens, so absolute safety lives
here and drift lives in ``scripts/ir_audit.py --check``.

The two stages DO each trace the entries (separate processes; jaxprs
don't serialize across them). That duplication is deliberate: a drifted
or missing golden must not block the safety audit and a rule finding must
not mask a drift report — the gates fail independently with their own
artifacts. Tracing is the cheap half of the graftir pipeline (the trainer
COMPILES, which dominate ir_audit's wall clock, are not repeated here).
"""

import argparse
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

# must run before jax initializes: entries trace on the 8-device virtual
# CPU mesh (same environment as the test suite and ir_audit)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--entries", help="comma-separated subset of entries")
    ap.add_argument("--format", choices=("text", "sarif"), default="text",
                    help="finding output format (sarif: a SARIF 2.1.0 "
                         "document on stdout for GitHub PR annotation; "
                         "the text report moves to stderr)")
    ap.add_argument("--report", metavar="DIR",
                    help="write report.txt + boundary_map.json + "
                         "precision.sarif into DIR")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    import jax
    jax.config.update("jax_default_matmul_precision", "float32")

    from dalle_tpu.analysis import contracts as C
    from dalle_tpu.analysis import ir_audit as A
    from dalle_tpu.analysis import precision_flow as pf

    if args.list_rules:
        for rule in pf.PRECISION_RULES:
            print(rule)
        return 0

    names = sorted(C.ENTRIES)
    if args.entries:
        names = [n.strip() for n in args.entries.split(",") if n.strip()]
        unknown = [n for n in names if n not in C.ENTRIES]
        if unknown:
            sys.exit(f"precision_audit.py: unknown entries: "
                     f"{', '.join(unknown)} (see ir_audit.py --list-entries)")

    from dalle_tpu.analysis.core import Finding, to_sarif

    failures = 0
    waived_count = 0
    boundary_map = {}
    lines = []
    sarif_findings = []
    # progress goes to stderr under --format sarif: stdout must stay a
    # single parseable SARIF document for `> precision.sarif` redirection
    progress_out = sys.stderr if args.format == "sarif" else sys.stdout
    for name in names:
        print(f"-- [trace] {name}", flush=True, file=progress_out)
        spec = C.ENTRIES[name]
        built = spec.build()
        rep = pf.analyze_fn(built.fn, built.args,
                            roles=getattr(built, "roles", None))
        boundary_map[name] = rep.boundary
        waivers, _problems = A.collect_waivers(spec.source)
        waiver = waivers.get("precision")
        for f in rep.findings:
            n = f" (x{f['count']})" if f.get("count", 1) > 1 else ""
            line = (f"{name} ({spec.source}): [{f['rule']}] {f['site']}: "
                    f"{f['detail']}{n}")
            if waiver is not None:
                lines.append(f"{line} [waived: {waiver.reason}]")
                waived_count += 1
            else:
                lines.append(line)
                failures += 1
                # findings anchor at the entry's source: the site names a
                # traced function, not a stable file:line in this repo
                sarif_findings.append(Finding(
                    f["rule"], spec.source, max(1, f.get("line", 1) or 1),
                    f"{name}: {f['site']}: {f['detail']}{n}"))

    scope = f"{len(names)} entr{'y' if len(names) == 1 else 'ies'}"
    if failures:
        lines.append(f"graftnum: {failures} precision finding(s) ({scope})")
        lines.append("fix the site, or waive with "
                     "'# graftir: allow=precision -- <reason>' in the "
                     "entry's source file")
    else:
        extra = f", {waived_count} waived" if waived_count else ""
        lines.append(f"graftnum: precision flow clean ({scope}{extra})")
    text = "\n".join(lines)
    rules = {r: r for r in pf.PRECISION_RULES}
    sarif = to_sarif(sarif_findings, "graftnum", rules)
    if args.format == "sarif":
        print(json.dumps(sarif, indent=1))
        print(text, file=sys.stderr)
    else:
        print(text)

    if args.report:
        os.makedirs(args.report, exist_ok=True)
        with open(os.path.join(args.report, "report.txt"), "w",
                  encoding="utf-8") as fh:
            fh.write(text + "\n")
        with open(os.path.join(args.report, "boundary_map.json"), "w",
                  encoding="utf-8") as fh:
            json.dump(boundary_map, fh, indent=1, sort_keys=True)
            fh.write("\n")
        with open(os.path.join(args.report, "precision.sarif"), "w",
                  encoding="utf-8") as fh:
            json.dump(sarif, fh, indent=1)
            fh.write("\n")

    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
