"""Train the in-repo perceptual net (models/data/tiny_perceptual.npz).

The reference downloads LPIPS weights (taming/util.py:5-44 + taming/modules/
losses/lpips.py:11-54: torchvision VGG16 + ``vgg.pth`` lin heads fitted to
human 2AFC judgments). This environment has zero egress, so the framework
ships its OWN perceptual net with the same structure (slices → unit-normalize
→ 1×1 lin → spatial mean), trained here in two stages:

  1. Trunk: shape/color/scale classification over the synthetic shapes corpus
     (data/synthetic.py — the same corpus the rainbow end-to-end tests train
     on). Classification forces the slices to carry edge/color/scale-selective
     features, which is what a perceptual distance reads.
  2. Lin heads: 2AFC-style ranking — for a reference image and two strengths
     of the same parametric distortion (blur / noise / contrast / posterize /
     color shift / block-downsample), the head must score the stronger
     distortion farther. This synthesizes the supervision style of the LPIPS
     lins from distortion magnitude instead of human judgments.

Run (TPU ~2 min, CPU ~15 min):
    python scripts/train_perceptual.py --out dalle_tpu/models/data/tiny_perceptual.npz
"""

from __future__ import annotations

import argparse
import sys
from functools import partial
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from dalle_tpu.data.synthetic import COLORS, SCALES, SHAPES, ShapesDataset
from dalle_tpu.models.lpips import (LPIPS, TINY_SLICES, VGG16Features,
                                    save_perceptual_weights)

# ---------------------------------------------------------------------------
# parametric distortions (strength s in [0, 1]; all pure jnp, jit-friendly)
# ---------------------------------------------------------------------------

def _box_blur(x, reps):
    k = jnp.ones((3, 3, 1, 1), x.dtype) / 9.0
    k = jnp.tile(k, (1, 1, 1, x.shape[-1]))

    def one(img):
        return jax.lax.conv_general_dilated(
            img, k, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=x.shape[-1])

    return jax.lax.fori_loop(0, reps, lambda _, v: one(v), x)


def distort(x, kind: int, s, key):
    """Apply distortion ``kind`` at strength ``s`` to NHWC images in [0,1]."""
    b = x.shape[0]
    if kind == 0:      # blur (1..6 box passes)
        return _box_blur(x, 1 + (s * 5.0).astype(jnp.int32))
    if kind == 1:      # additive gaussian noise
        return jnp.clip(x + jax.random.normal(key, x.shape) * 0.25 * s, 0, 1)
    if kind == 2:      # contrast collapse toward the per-image mean
        mean = jnp.mean(x, axis=(1, 2, 3), keepdims=True)
        return x * (1 - 0.8 * s) + mean * 0.8 * s
    if kind == 3:      # posterize (quantize levels 16 → 2)
        levels = jnp.maximum(16.0 * (1 - s), 2.0)
        return jnp.round(x * levels) / levels
    if kind == 4:      # channel shift (hue-ish): blend toward rolled channels
        return x * (1 - 0.7 * s) + jnp.roll(x, 1, axis=-1) * 0.7 * s
    if kind == 5:      # block corruption: average-pool k×k then upsample
        size = x.shape[1]
        k = 1 + (s * 7.0).astype(jnp.int32)

        def pool(img):
            idx = (jnp.arange(size) // k) * k
            return img[:, idx][:, :, idx]

        return pool(x)
    raise ValueError(kind)


N_KINDS = 6


# ---------------------------------------------------------------------------
# stage 1: trunk classification
# ---------------------------------------------------------------------------

class _Classifier(nn.Module):
    """GAP over every slice → shared hidden → 3 label heads."""

    @nn.compact
    def __call__(self, feats):
        h = jnp.concatenate([jnp.mean(f, axis=(1, 2)) for f in feats], -1)
        h = nn.relu(nn.Dense(256)(h))
        return (nn.Dense(len(SHAPES))(h), nn.Dense(len(COLORS))(h),
                nn.Dense(len(SCALES))(h))


def train_trunk(images, labels, *, steps: int, batch: int, seed: int):
    """images in [-1, 1]; labels: (shape_id, color_id, scale_id) arrays."""
    trunk = VGG16Features(slices=TINY_SLICES)
    head = _Classifier()
    k0, k1 = jax.random.split(jax.random.PRNGKey(seed))
    x0 = images[:2]
    tp = trunk.init(k0, x0)
    hp = head.init(k1, trunk.apply(tp, x0))
    params = {"trunk": tp, "head": hp}
    tx = optax.adam(1e-3)
    opt = tx.init(params)

    @partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt, x, ys, yc, ysc):
        def loss_fn(p):
            feats = trunk.apply(p["trunk"], x)
            ls, lc, lsc = head.apply(p["head"], feats)
            ce = optax.softmax_cross_entropy_with_integer_labels
            loss = (ce(ls, ys).mean() + ce(lc, yc).mean() + ce(lsc, ysc).mean())
            acc = jnp.mean((jnp.argmax(ls, -1) == ys) & (jnp.argmax(lc, -1) == yc)
                           & (jnp.argmax(lsc, -1) == ysc))
            return loss, acc

        (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt = tx.update(grads, opt, params)
        return optax.apply_updates(params, updates), opt, loss, acc

    rng = np.random.RandomState(seed)
    n = images.shape[0]
    for i in range(steps):
        idx = rng.randint(0, n, batch)
        params, opt, loss, acc = step(params, opt, images[idx],
                                      labels[0][idx], labels[1][idx],
                                      labels[2][idx])
        if i % 100 == 0 or i == steps - 1:
            print(f"  trunk step {i}: loss {float(loss):.4f} "
                  f"acc(all-3) {float(acc):.3f}", flush=True)
    return params["trunk"]


# ---------------------------------------------------------------------------
# stage 2: lin heads on distortion ranking
# ---------------------------------------------------------------------------

def train_lins(model: LPIPS, lpips_params, images, *, steps: int, batch: int,
               seed: int, margin: float = 0.05):
    """Hinge-rank d(x, weak) + margin < d(x, strong), within distortion type.
    Only the lin heads train; the trunk stays frozen."""
    lin_keys = [k for k in lpips_params["params"] if k.startswith("lin")]
    tx = optax.adam(3e-3)

    def split(p):
        lins = {k: p["params"][k] for k in lin_keys}
        return lins

    def join(lins):
        newp = dict(lpips_params["params"])
        newp.update(lins)
        return {"params": newp}

    lins = split(lpips_params)
    opt = tx.init(lins)

    @partial(jax.jit, donate_argnums=(0, 1))
    def step(lins, opt, x, weak, strong):
        def loss_fn(lins):
            p = join(lins)
            d_w = model.apply(p, x, weak)
            d_s = model.apply(p, x, strong)
            rank = jnp.mean(jax.nn.relu(margin + d_w - d_s))
            # keep the overall scale anchored (ranking alone is scale-free)
            anchor = (jnp.mean(d_s) - 1.0) ** 2 * 0.01
            acc = jnp.mean(d_s > d_w)
            return rank + anchor, acc

        (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(lins)
        updates, opt = tx.update(grads, opt, lins)
        return optax.apply_updates(lins, updates), opt, loss, acc

    rng = np.random.RandomState(seed)
    n = images.shape[0]
    for i in range(steps):
        idx = rng.randint(0, n, batch)
        kind = int(rng.randint(N_KINDS))
        key = jax.random.PRNGKey(rng.randint(1 << 30))
        x, weak, strong = _make_pairs(images[idx], kind, key)
        lins, opt, loss, acc = step(lins, opt, x, weak, strong)
        if i % 100 == 0 or i == steps - 1:
            print(f"  lin step {i}: rank-loss {float(loss):.4f} "
                  f"pair-acc {float(acc):.3f}", flush=True)
    return join(lins)


@partial(jax.jit, static_argnums=(1,))
def _make_pairs(x01, kind, key):
    """x in [0,1] → (x, weak, strong) in [-1,1] with s_weak < s_strong."""
    kw, ks, kd1, kd2 = jax.random.split(key, 4)
    s_weak = jax.random.uniform(kw, (), minval=0.05, maxval=0.45)
    s_strong = s_weak + jax.random.uniform(ks, (), minval=0.25, maxval=0.5)
    weak = distort(x01, kind, s_weak, kd1)
    strong = distort(x01, kind, jnp.minimum(s_strong, 1.0), kd2)
    to = lambda t: t * 2.0 - 1.0
    return to(x01), to(weak), to(strong)


def rank_accuracy(model, params, images, *, seed: int, trials: int = 60):
    """Held-out 2AFC accuracy across all distortion types."""
    rng = np.random.RandomState(seed)
    hits = total = 0
    for _ in range(trials):
        idx = rng.randint(0, images.shape[0], 16)
        kind = int(rng.randint(N_KINDS))
        key = jax.random.PRNGKey(rng.randint(1 << 30))
        x, weak, strong = _make_pairs(images[idx], kind, key)
        d_w = model.apply(params, x, weak)
        d_s = model.apply(params, x, strong)
        hits += int(jnp.sum(d_s > d_w))
        total += d_w.shape[0]
    return hits / total


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=str(Path(__file__).resolve().parent.parent /
                                         "dalle_tpu/models/data/tiny_perceptual.npz"))
    ap.add_argument("--image_size", type=int, default=64)
    ap.add_argument("--variants", type=int, default=6)
    ap.add_argument("--steps_cls", type=int, default=800)
    ap.add_argument("--steps_lin", type=int, default=600)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)

    ds = ShapesDataset(image_size=args.image_size, variants=args.variants,
                       seed=args.seed)
    print(f"rendering {len(ds)} shape images…", flush=True)
    samples = [ds[i] for i in range(len(ds))]
    images01 = jnp.asarray(np.stack([s.image for s in samples]),
                           jnp.float32) / 255.0
    shape_ids = {s: i for i, s in enumerate(SHAPES)}
    color_ids = {c: i for i, c in enumerate(COLORS)}
    scale_ids = {s: i for i, s in enumerate(SCALES)}
    labels = (np.array([shape_ids[s.label[1]] for s in samples]),
              np.array([color_ids[s.label[0]] for s in samples]),
              np.array([scale_ids[s.label[2]] for s in samples]))
    # trunk consumes the LPIPS input convention ([-1,1] + ImageNet scaling
    # happens inside LPIPS; for classification train on the same range)
    images = images01 * 2.0 - 1.0

    print("stage 1: trunk classification", flush=True)
    trunk_params = train_trunk(images, labels, steps=args.steps_cls,
                               batch=args.batch, seed=args.seed)

    model = LPIPS(slices=TINY_SLICES)
    x0 = images[:2]
    params = jax.device_get(model.init(jax.random.PRNGKey(args.seed), x0, x0))
    params["params"]["vgg"] = jax.device_get(trunk_params)["params"]

    print("stage 2: lin heads on distortion ranking", flush=True)
    params = train_lins(model, params, images01, steps=args.steps_lin,
                        batch=32, seed=args.seed + 1)

    acc = rank_accuracy(model, params, images01, seed=args.seed + 2)
    print(f"held-out 2AFC ranking accuracy: {acc:.3f}", flush=True)

    save_perceptual_weights(params, args.out)
    nbytes = Path(args.out).stat().st_size
    print(f"saved {args.out} ({nbytes / 1e6:.2f} MB)", flush=True)


if __name__ == "__main__":
    main()
