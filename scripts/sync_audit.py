#!/usr/bin/env python
"""graftsync CLI — static concurrency audit of the threaded control plane.

    python scripts/sync_audit.py --check            # CI gate (default)
    python scripts/sync_audit.py --update           # regenerate the golden
    python scripts/sync_audit.py --explain          # print the lock model
    python scripts/sync_audit.py --list-rules
    python scripts/sync_audit.py --check --format sarif > sync.sarif
    python scripts/sync_audit.py --check --report sync_artifacts

--check builds the whole-module concurrency model (lock inventory,
guarded-field map, lock-acquisition graph, thread entries) over the sync
roots and fails on: rule findings (lockset violations, acquisition-order
cycles, blocking calls under a lock, lifecycle hygiene), waiver problems,
or drift of the acquisition graph against the golden in contracts/sync.json.
Intentional lock/edge changes are accepted with --update (commit the JSON
diff — it is the PR's reviewable locking story). The runtime half is
dalle_tpu/obs/lockorder.py: gateway_smoke/fleet_smoke record the OBSERVED
acquisition graph and assert it is acyclic and a subgraph of this golden.

Waivers are source comments on the finding's line or the line above
(``# graftsync: allow=blocking-under-lock -- <reason>``); see
docs/ANALYSIS.md.
"""

import argparse
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

# pure-AST analysis — but the analysis package import pulls jax via the
# vmem rule; keep it on CPU so auditing never touches an accelerator
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    mode = ap.add_mutually_exclusive_group()
    mode.add_argument("--check", action="store_true",
                      help="findings + golden drift (default)")
    mode.add_argument("--update", action="store_true",
                      help="regenerate the golden lock graph")
    mode.add_argument("--explain", action="store_true",
                      help="pretty-print the live concurrency model")
    ap.add_argument("--contract",
                    default=os.path.join(ROOT, "contracts", "sync.json"),
                    help="golden path (default: contracts/sync.json)")
    ap.add_argument("--format", choices=("text", "sarif"), default="text",
                    help="finding output format (sarif: a SARIF 2.1.0 "
                         "document on stdout for GitHub PR annotation)")
    ap.add_argument("--report", metavar="DIR",
                    help="write report.txt + findings.json + sync.sarif "
                         "into DIR (CI artifact)")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    from dalle_tpu.analysis import rules_sync as R
    from dalle_tpu.analysis.core import to_sarif

    if args.list_rules:
        width = max(len(n) for n in R.SYNC_RULES)
        for name, desc in sorted(R.SYNC_RULES.items()):
            print(f"{name:<{width}}  {desc}")
        return 0

    if args.explain:
        report = R.audit(ROOT, args.contract, update=False)
        print(R.explain(report.model))
        return 0

    report = R.audit(ROOT, args.contract, update=bool(args.update))
    scope = (f"{len(report.model.locks)} locks, "
             f"{len(report.model.edges)} edges, "
             f"{len(report.model.thread_entries)} thread entries")
    text = R.render_report(report, scope)
    if args.format == "sarif":
        print(json.dumps(to_sarif(report.findings, "graftsync",
                                  R.SYNC_RULES), indent=1))
        print(text, file=sys.stderr)
    else:
        print(text)

    if args.report:
        os.makedirs(args.report, exist_ok=True)
        with open(os.path.join(args.report, "report.txt"), "w",
                  encoding="utf-8") as fh:
            fh.write(text + "\n")
        with open(os.path.join(args.report, "findings.json"), "w",
                  encoding="utf-8") as fh:
            json.dump({"findings": [vars(f) for f in report.findings],
                       "waived": [{**vars(f), "reason": r}
                                  for f, r in report.waived],
                       "problems": report.problems,
                       "drift": report.drift}, fh, indent=1, sort_keys=True)
            fh.write("\n")
        with open(os.path.join(args.report, "sync.sarif"), "w",
                  encoding="utf-8") as fh:
            json.dump(to_sarif(report.findings, "graftsync", R.SYNC_RULES),
                      fh, indent=1)
            fh.write("\n")

    # distinct exit codes, graftir-style: 1 = findings/waiver problems/
    # graph drift (a regression); 3 = ONLY a missing golden (first run —
    # needs --update, not a code change)
    if report.failed:
        return 1
    if report.missing:
        print("sync_audit: exit 3 — golden lock graph MISSING; run "
              "scripts/sync_audit.py --update and commit contracts/sync.json")
        return 3
    return 0


if __name__ == "__main__":
    sys.exit(main())
