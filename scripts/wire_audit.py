#!/usr/bin/env python
"""graftwire CLI — static wire-protocol + lifecycle audit of the fleet RPC.

    python scripts/wire_audit.py --check            # CI gate (default)
    python scripts/wire_audit.py --update           # regenerate the golden
    python scripts/wire_audit.py --explain          # print the protocol
    python scripts/wire_audit.py --list-rules
    python scripts/wire_audit.py --check --format sarif > wire.sarif
    python scripts/wire_audit.py --check --report wire_artifacts

--check builds the cross-process protocol model (sender schemas, receiver
schemas, verb dispatch, lifecycle event emissions) over the wire roots
(fleet/, gateway/, serve/, scripts/serve_replica.py) and fails on: rule
findings (unread/unsourced fields, optional-field subscripts, verb
orphans, undeclared lifecycle transitions), waiver problems, or drift of
the protocol against the golden in contracts/wire.json. An intentional
protocol change is accepted with --update (commit the JSON diff — it is
the PR's reviewable wire story, naming both endpoints of every changed
field). The runtime half is dalle_tpu/obs/wiretap.py: fleet_smoke/
gateway_smoke tap every live frame and assert observed ⊆ this golden.

Waivers are source comments on the finding's line or the line above
(``# graftwire: allow=wire-field-unread -- <reason>``); see
docs/ANALYSIS.md.
"""

import argparse
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

# pure-AST analysis — but the analysis package import pulls jax via the
# vmem rule; keep it on CPU so auditing never touches an accelerator
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    mode = ap.add_mutually_exclusive_group()
    mode.add_argument("--check", action="store_true",
                      help="findings + golden drift (default)")
    mode.add_argument("--update", action="store_true",
                      help="regenerate the golden protocol contract")
    mode.add_argument("--explain", action="store_true",
                      help="pretty-print the live protocol model")
    ap.add_argument("--contract",
                    default=os.path.join(ROOT, "contracts", "wire.json"),
                    help="golden path (default: contracts/wire.json)")
    ap.add_argument("--format", choices=("text", "sarif"), default="text",
                    help="finding output format (sarif: a SARIF 2.1.0 "
                         "document on stdout for GitHub PR annotation)")
    ap.add_argument("--report", metavar="DIR",
                    help="write report.txt + findings.json + wire.sarif "
                         "into DIR (CI artifact)")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    from dalle_tpu.analysis import rules_wire as R
    from dalle_tpu.analysis.core import to_sarif

    if args.list_rules:
        width = max(len(n) for n in R.WIRE_RULES)
        for name, desc in sorted(R.WIRE_RULES.items()):
            print(f"{name:<{width}}  {desc}")
        return 0

    if args.explain:
        report = R.audit(ROOT, args.contract, update=False)
        print(R.explain(report.model))
        return 0

    report = R.audit(ROOT, args.contract, update=bool(args.update))
    n_chan = sum(1 for (v, d, k) in report.model.channels()
                 if not (d == "stream" and k is None))
    scope = (f"{n_chan} channels, "
             f"{len({u.verb for u in report.model.sent_verbs})} verbs, "
             f"{len({e.name for e in report.model.events})} event names")
    text = R.render_report(report, scope)
    if args.format == "sarif":
        print(json.dumps(to_sarif(report.findings, "graftwire",
                                  R.WIRE_RULES), indent=1))
        print(text, file=sys.stderr)
    else:
        print(text)

    if args.report:
        os.makedirs(args.report, exist_ok=True)
        with open(os.path.join(args.report, "report.txt"), "w",
                  encoding="utf-8") as fh:
            fh.write(text + "\n")
        with open(os.path.join(args.report, "findings.json"), "w",
                  encoding="utf-8") as fh:
            json.dump({"findings": [vars(f) for f in report.findings],
                       "waived": [{**vars(f), "reason": r}
                                  for f, r in report.waived],
                       "problems": report.problems,
                       "drift": report.drift}, fh, indent=1, sort_keys=True)
            fh.write("\n")
        with open(os.path.join(args.report, "wire.sarif"), "w",
                  encoding="utf-8") as fh:
            json.dump(to_sarif(report.findings, "graftwire", R.WIRE_RULES),
                      fh, indent=1)
            fh.write("\n")

    # distinct exit codes, graftir-style: 1 = findings/waiver problems/
    # contract drift (a regression); 3 = ONLY a missing golden (first run —
    # needs --update, not a code change)
    if report.failed:
        return 1
    if report.missing:
        print("wire_audit: exit 3 — golden protocol contract MISSING; run "
              "scripts/wire_audit.py --update and commit contracts/wire.json")
        return 3
    return 0


if __name__ == "__main__":
    sys.exit(main())
