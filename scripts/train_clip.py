#!/usr/bin/env python
"""Train a CLIP reranker on TPU (or the CPU mesh).

The reference ships the CLIP model + symmetric-CE loss
(dalle_pytorch/dalle_pytorch.py:256-332) but no training script — CLIP's only
job there is reranking generations (:553-555). This CLI completes the flow:
train here, then rerank with ``scripts/generate.py --clip_path``.

Example:
  python scripts/sampler.py --outdir /tmp/shapes --count 256 --image_size 64
  python scripts/train_clip.py --image_text_folder /tmp/shapes \
      --image_size 64 --patch_size 8 --dim 128 --depth 2 --epochs 2
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import (add_compile_cache_args, add_health_args,  # noqa: E402
                     add_resilience_args, install_resilience,
                     add_overlap_args, add_profiler_args,
                     enable_compile_cache, health_obs_kwargs,
                     install_health_recorder, install_sigusr2_profiler,
                     overlap_train_kwargs)


def build_parser():
    ap = argparse.ArgumentParser(description=__doc__)
    data = ap.add_argument_group("data")
    data.add_argument("--image_text_folder", type=str, default=None)
    data.add_argument("--synthetic", action="store_true")
    data.add_argument("--text_from_filename", action="store_true")
    data.add_argument("--image_size", type=int, default=256)

    tok = ap.add_argument_group("tokenizer")
    tok.add_argument("--tokenizer", type=str, default="simple",
                     choices=["simple", "yttm", "hug", "chinese"])
    tok.add_argument("--bpe_path", type=str, default=None)

    model = ap.add_argument_group("model")
    model.add_argument("--dim", type=int, default=512,
                       help="shared width for text/image encoders + latent")
    model.add_argument("--depth", type=int, default=6)
    model.add_argument("--heads", type=int, default=8)
    model.add_argument("--text_seq_len", type=int, default=256)
    model.add_argument("--patch_size", type=int, default=32)
    model.add_argument("--num_text_tokens", type=int, default=None,
                       help="default: tokenizer vocab size")

    train = ap.add_argument_group("training")
    train.add_argument("--epochs", type=int, default=20)
    train.add_argument("--batch_size", type=int, default=32)
    train.add_argument("--learning_rate", type=float, default=3e-4)
    train.add_argument("--clip_grad_norm", type=float, default=0.5)
    train.add_argument("--output_dir", type=str, default="./clip_ckpt")
    train.add_argument("--save_every_n_steps", type=int, default=1000)
    train.add_argument("--seed", type=int, default=42)
    train.add_argument("--steps", type=int, default=None)
    train.add_argument("--scan_steps", type=int, default=1,
                       help="k optimizer steps per device dispatch (a NaN "
                            "rollback rewinds the whole k-step group)")
    train.add_argument("--no_preflight", action="store_true")

    add_overlap_args(ap)
    add_health_args(ap)
    add_resilience_args(ap)
    add_compile_cache_args(ap)
    add_profiler_args(ap)
    from dalle_tpu.parallel import wrap_arg_parser
    wrap_arg_parser(ap)
    return ap


def main(argv=None):
    args = build_parser().parse_args(argv)
    if not (args.image_text_folder or args.synthetic):
        print("error: provide --image_text_folder or --synthetic",
              file=sys.stderr)
        return 2

    enable_compile_cache(args)
    install_sigusr2_profiler(os.path.join(args.output_dir, "profile"),
                             args)
    import numpy as np
    from dalle_tpu.config import (ClipConfig, ObsConfig, OptimConfig,
                                  TrainConfig)
    from dalle_tpu.parallel import set_backend_from_args
    from dalle_tpu.text.tokenizer import get_tokenizer
    from dalle_tpu.train.trainer_clip import CLIPTrainer

    backend = set_backend_from_args(args).initialize()
    backend.check_batch_size(args.batch_size)
    is_root = backend.is_root_worker()

    tok_kw = {"bpe_path": args.bpe_path} if args.bpe_path else {}
    tokenizer = get_tokenizer(args.tokenizer, **tok_kw)
    num_text_tokens = args.num_text_tokens or max(tokenizer.vocab_size, 256)
    if num_text_tokens < tokenizer.vocab_size:
        print(f"error: --num_text_tokens {num_text_tokens} < tokenizer vocab "
              f"{tokenizer.vocab_size}", file=sys.stderr)
        return 2

    model_cfg = ClipConfig(
        dim_text=args.dim, dim_image=args.dim, dim_latent=args.dim,
        num_text_tokens=num_text_tokens, text_enc_depth=args.depth,
        text_seq_len=args.text_seq_len, text_heads=args.heads,
        visual_enc_depth=args.depth, visual_heads=args.heads,
        visual_image_size=args.image_size, visual_patch_size=args.patch_size)
    train_cfg = TrainConfig(
        runtime_lr_scale=args.breach_actions,
        batch_size=args.batch_size, epochs=args.epochs, seed=args.seed,
        checkpoint_dir=args.output_dir,
        save_every_steps=args.save_every_n_steps,
        preflight_checkpoint=not args.no_preflight, scan_steps=args.scan_steps,
        **overlap_train_kwargs(args),
        obs=ObsConfig(**health_obs_kwargs(args)),
        optim=OptimConfig(learning_rate=args.learning_rate,
                          grad_clip_norm=args.clip_grad_norm))
    install_health_recorder(args, os.path.join(args.output_dir,
                                               "health_bundles"))

    trainer = CLIPTrainer(model_cfg, train_cfg, backend=backend)

    def encode_batch(images, captions):
        text = tokenizer.tokenize(list(captions), args.text_seq_len,
                                  truncate_text=True)
        return text, np.asarray(images, np.float32)

    if args.synthetic:
        from dalle_tpu.data.synthetic import ShapesDataset, batch_iterator
        ds = ShapesDataset(image_size=args.image_size)
        raw = batch_iterator(ds, args.batch_size, seed=args.seed,
                             epochs=args.epochs)
    else:
        from dalle_tpu.data.text_image import TextImageDataset
        ds = TextImageDataset(args.image_text_folder,
                              image_size=args.image_size, shuffle=True,
                              seed=args.seed,
                              text_from_filename=args.text_from_filename)
        raw = ds.batches(args.batch_size, epochs=args.epochs)
    batches = (encode_batch(imgs, caps) for imgs, caps in raw)

    if is_root:
        print(f"CLIP: {trainer.num_params / 1e6:.1f}M params; "
              f"mesh {dict(trainer.mesh.shape)}")
    log = print if is_root else (lambda *a, **k: None)
    install_resilience(args, trainer, log=log)
    trainer.fit(batches, steps=args.steps, log=log)

    final = int(trainer.state.step)
    if trainer.ckpt.latest_step() != final:
        trainer.ckpt.save(final, trainer.state, trainer._meta())
    trainer.ckpt.wait_until_finished()   # final step durable before exit
    if is_root:
        print(f"done at step {final}; checkpoints in {args.output_dir}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
