"""Shared CLI plumbing: VAE reconstitution and checkpoint-params loading.

Reference: legacy/train_dalle.py:249-299 — the VAE precedence chain
(resume-embedded params > ``--vae_path`` trained dVAE > ``--taming`` VQGAN >
OpenAI pretrained) — and legacy/generate.py:82-106 (rebuild exact model from
checkpoint-embedded hparams + vae_class_name).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def load_model_checkpoint(ckpt_dir: str, expect_class: str, config_cls,
                          init_fn):
    """Generic checkpoint reconstitution from embedded metadata (reference
    legacy/generate.py:82-106): validate model_class, rebuild the model from
    ``hparams``, restore params. Returns (model, params, meta)."""
    import jax
    from dalle_tpu.config import OptimConfig
    from dalle_tpu.train.checkpoints import CheckpointManager
    from dalle_tpu.train.train_state import TrainState, make_optimizer

    mgr = CheckpointManager(ckpt_dir)
    meta = mgr.load_metadata()
    if meta is None or meta.get("model_class") != expect_class:
        raise ValueError(f"{ckpt_dir} is not a {expect_class} checkpoint "
                         f"(model_class={meta and meta.get('model_class')})")
    cfg = config_cls.from_dict(meta["hparams"])
    optim = OptimConfig.from_dict(meta.get("train", {}).get("optim", {})) \
        if meta.get("train") else OptimConfig()
    model, params = init_fn(cfg, jax.random.PRNGKey(0))
    template = TrainState.create(apply_fn=model.apply, params=params,
                                 tx=make_optimizer(optim))
    state, _ = mgr.restore(template)
    mgr.close()
    return model, state.params, meta


def save_vae_sidecar(output_dir: str, vae):
    """Embed the (frozen) VAE weights+hparams inside the DALL·E checkpoint
    directory, so generation needs only ``--dalle_path`` — the reference's
    checkpoints carry the vae as a submodule of the DALLE state dict plus
    ``vae_params``/``vae_class_name`` (legacy/train_dalle.py:535-582).
    Pretrained wrappers (OpenAI/VQGAN) are skipped: they rebuild from their
    own cached artifacts, exactly like the reference (generate.py:93-100)."""
    from dalle_tpu.models.wrapper import DiscreteVAEAdapter
    if type(vae) is not DiscreteVAEAdapter:
        return
    from dalle_tpu.train.checkpoints import CheckpointManager
    mgr = CheckpointManager(os.path.join(output_dir, "vae"))
    mgr.save(0, vae.params, {"vae_class_name": type(vae).__name__,
                             "hparams": vae.model.cfg.to_dict()})
    mgr.close()


def load_vae_sidecar(ckpt_dir: str):
    """Rebuild the VAE embedded by ``save_vae_sidecar``; None if absent."""
    vdir = os.path.join(ckpt_dir, "vae")
    if not os.path.isdir(vdir):
        return None
    import jax
    from dalle_tpu.config import DVAEConfig
    from dalle_tpu.models.dvae import init_dvae
    from dalle_tpu.models.wrapper import DiscreteVAEAdapter
    from dalle_tpu.train.checkpoints import CheckpointManager

    mgr = CheckpointManager(vdir)
    meta = mgr.load_metadata()
    if meta is None or meta.get("vae_class_name") != "DiscreteVAEAdapter":
        mgr.close()
        return None
    cfg = DVAEConfig.from_dict(meta["hparams"])
    model, template = init_dvae(cfg, jax.random.PRNGKey(0))
    params, _ = mgr.restore(template)
    mgr.close()
    return DiscreteVAEAdapter(model, params)


def load_dvae_adapter(ckpt_dir: str):
    """Restore a scripts/train_vae.py checkpoint into a DiscreteVAEAdapter."""
    from dalle_tpu.config import DVAEConfig
    from dalle_tpu.models.dvae import init_dvae
    from dalle_tpu.models.wrapper import DiscreteVAEAdapter

    model, params, _ = load_model_checkpoint(ckpt_dir, "DiscreteVAE",
                                             DVAEConfig, init_dvae)
    return DiscreteVAEAdapter(model, params)


def build_vae_from_args(args, backend=None):
    """The reference's VAE precedence chain for CLIs (train_dalle.py:264-299).
    Returns a VAEAdapter."""
    if getattr(args, "vae_path", None):
        return load_dvae_adapter(args.vae_path)
    if getattr(args, "taming", False) or getattr(args, "vqgan_model_path", None):
        from dalle_tpu.models.pretrained import VQGanVAE
        return VQGanVAE.from_pretrained(
            vqgan_model_path=getattr(args, "vqgan_model_path", None),
            vqgan_config_path=getattr(args, "vqgan_config_path", None),
            backend=backend)
    if getattr(args, "untrained_vae", False):
        # smoke-test path: random dVAE, no pretrained weights needed
        import jax
        from dalle_tpu.config import DVAEConfig
        from dalle_tpu.models.dvae import init_dvae
        from dalle_tpu.models.wrapper import DiscreteVAEAdapter
        cfg = DVAEConfig(image_size=args.image_size,
                         num_tokens=getattr(args, "untrained_vae_tokens", 512),
                         codebook_dim=64,
                         num_layers=getattr(args, "untrained_vae_layers", 2),
                         hidden_dim=32)
        model, params = init_dvae(cfg, jax.random.PRNGKey(0))
        return DiscreteVAEAdapter(model, params)
    from dalle_tpu.models.pretrained import OpenAIDiscreteVAE
    return OpenAIDiscreteVAE.from_pretrained(backend=backend)


def add_vae_args(parser):
    grp = parser.add_argument_group("vae")
    grp.add_argument("--vae_path", type=str, default=None,
                     help="checkpoint dir from scripts/train_vae.py")
    grp.add_argument("--taming", action="store_true",
                     help="use the pretrained taming VQGAN")
    grp.add_argument("--vqgan_model_path", type=str, default=None)
    grp.add_argument("--vqgan_config_path", type=str, default=None)
    grp.add_argument("--untrained_vae", action="store_true",
                     help="random dVAE (smoke tests; no download needed)")
    grp.add_argument("--untrained_vae_tokens", type=int, default=512)
    grp.add_argument("--untrained_vae_layers", type=int, default=2)
    return parser


def save_image_grid(images, path):
    """images (b, H, W, C) float [0,1] → one PNG per row dir-less save."""
    import numpy as np
    from PIL import Image
    arr = (np.asarray(images) * 255).clip(0, 255).astype("uint8")
    for i, im in enumerate(arr):
        Image.fromarray(im).save(path.format(i))


def add_compile_cache_args(parser):
    """Persistent XLA compilation cache flags, shared by every CLI (train
    AND serve): a rejoining worker or a scaled-up serving replica reads
    compiled programs back from disk instead of repaying XLA (the
    trace is still paid — gateway AOT bundles skip that too, see
    docs/SERVING.md)."""
    grp = parser.add_argument_group("compilation cache (docs/SERVING.md)")
    grp.add_argument("--compile_cache_dir", type=str,
                     default="~/.cache/dalle_tpu/xla_cache",
                     help="persistent XLA compilation cache directory "
                          "(content-addressed; safe to share across "
                          "processes and runs)")
    grp.add_argument("--no_compile_cache", action="store_true",
                     help="disable the persistent compilation cache "
                          "(every process recompiles from scratch)")
    return parser


def enable_compile_cache(args) -> bool:
    """Apply add_compile_cache_args flags. Call BEFORE the first jit
    dispatch — programs compiled earlier in the process are not
    retro-cached. Returns True when the cache was enabled."""
    if getattr(args, "no_compile_cache", False):
        return False
    from dalle_tpu.utils.misc import enable_compilation_cache
    enable_compilation_cache(args.compile_cache_dir)
    return True


def add_profiler_args(parser):
    """On-demand ``jax.profiler`` capture, shared by train AND serve CLIs:
    ``kill -USR2 <pid>`` records a bounded trace into the artifacts dir —
    the "the p99 is weird RIGHT NOW" tool, with zero cost until the signal
    arrives and a hard stop after ``--profiler_capture_s`` so a forgotten
    capture can't fill the disk."""
    grp = parser.add_argument_group("on-demand profiler "
                                    "(docs/OBSERVABILITY.md)")
    grp.add_argument("--profiler_dir", type=str, default=None,
                     help="SIGUSR2 target dir for bounded jax.profiler "
                          "traces (default: <output/artifacts dir>/profile;"
                          " 'off' disables the handler)")
    grp.add_argument("--profiler_capture_s", type=float, default=5.0,
                     help="seconds per capture (the bound)")
    return parser


def install_sigusr2_profiler(default_dir: str, args=None) -> bool:
    """Install the SIGUSR2 handler (main thread only — call from the CLI's
    main). Each signal starts one ``jax.profiler`` trace into a timestamped
    subdir and a daemon timer stops it after the bound; a signal landing
    mid-capture is ignored (one capture at a time). Returns False when
    disabled or uninstallable."""
    import signal
    import threading
    import time

    outdir = default_dir
    capture_s = 5.0
    if args is not None:
        if getattr(args, "profiler_dir", None) == "off":
            return False
        outdir = getattr(args, "profiler_dir", None) or default_dir
        capture_s = float(getattr(args, "profiler_capture_s", 5.0))
    state = {"active": False}

    def _stop():
        import jax
        try:
            jax.profiler.stop_trace()
        except Exception as exc:  # noqa: BLE001 - a failed stop must not
            # kill the timer thread; the next capture starts a fresh trace
            print(f"[graftscope] profiler stop failed: {exc!r}")
        state["active"] = False

    def _handler(_sig, _frame):
        if state["active"]:
            return
        state["active"] = True
        import jax
        path = os.path.join(outdir, time.strftime("profile_%Y%m%d_%H%M%S"))
        os.makedirs(path, exist_ok=True)
        try:
            jax.profiler.start_trace(path)
        except Exception as exc:  # noqa: BLE001 - an already-running or
            # unsupported profiler must not kill the training/serving loop
            # the signal interrupted
            print(f"[graftscope] profiler start failed: {exc!r}")
            state["active"] = False
            return
        print(f"[graftscope] SIGUSR2: profiling {capture_s:.1f}s → {path}",
              flush=True)
        threading.Timer(capture_s, _stop).start()

    try:
        signal.signal(signal.SIGUSR2, _handler)
    except (ValueError, AttributeError):   # non-main thread / platform
        return False
    return True


def add_health_args(parser):
    """graftpulse model-health flags shared by every train CLI
    (docs/OBSERVABILITY.md "Model health"): the in-jit taps + anomaly
    sentries. Off by default — enabling changes the compiled step program
    (pinned by the graftir goldens, which build health-on)."""
    grp = parser.add_argument_group("model health (graftpulse, "
                                    "docs/OBSERVABILITY.md)")
    grp.add_argument("--health", action="store_true",
                     help="fuse per-layer-group grad/param/update/"
                          "non-finite taps (and codebook vitals on the VAE "
                          "trainers) into the jitted step and run the "
                          "anomaly sentries — zero added host syncs; "
                          "breaches fire dalle_health_* gauges, flight "
                          "bundles and the obs_report MODEL-HEALTH verdict")
    grp.add_argument("--health_group_depth", type=int, default=1,
                     help="pytree depth for layer groups (1 = model "
                          "subtrees)")
    grp.add_argument("--health_loss_z", type=float, default=6.0,
                     help="loss-spike z-score threshold")
    grp.add_argument("--health_grad_factor", type=float, default=10.0,
                     help="grad-norm explosion factor over the EMA")
    grp.add_argument("--health_perplexity_floor", type=float, default=4.0,
                     help="codebook-collapse floor (usage perplexity)")
    grp.add_argument("--health_flight_dir", type=str, default=None,
                     help="configure a flight recorder here so health "
                          "breaches dump post-mortem bundles (default: "
                          "<output_dir>/health_bundles when --health)")
    return parser


def health_obs_kwargs(args) -> dict:
    """ObsConfig kwargs from add_health_args flags."""
    return {
        "health": args.health,
        "health_group_depth": args.health_group_depth,
        "health_loss_z": args.health_loss_z,
        "health_grad_factor": args.health_grad_factor,
        "health_perplexity_floor": args.health_perplexity_floor,
    }


def install_health_recorder(args, default_dir: str) -> bool:
    """With --health, make sure a flight recorder exists so breach bundles
    have somewhere to land (an already-configured recorder wins). Returns
    True when a recorder was installed here."""
    if not getattr(args, "health", False):
        return False
    from dalle_tpu import obs
    if obs.get_recorder() is not None:
        return False
    obs.configure_recorder(getattr(args, "health_flight_dir", None)
                           or default_dir)
    return True


def add_resilience_args(parser):
    """graftmend flags shared by every train CLI (docs/RESILIENCE.md):
    the SIGTERM graceful-preemption handler (default ON — the k8s/TPU
    preemption contract) and the breach→action automation over the
    graftpulse sentries (opt-in; needs --health for the detectors to see
    anything)."""
    grp = parser.add_argument_group("resilience (graftmend, "
                                    "docs/RESILIENCE.md)")
    grp.add_argument("--no_preemption_handler", action="store_true",
                     help="do NOT install the SIGTERM handler (default: "
                          "SIGTERM finishes the in-flight step, takes a "
                          "synchronous drained save, and exits 0)")
    grp.add_argument("--breach_actions", action="store_true",
                     help="act on graftpulse breaches: nan-precursor → "
                          "preemptive snapshot, grad-explosion → rollback "
                          "+ lr cut, codebook-collapse → lr cut + gumbel "
                          "re-anneal (pair with --health)")
    grp.add_argument("--lr_cut_factor", type=float, default=0.5,
                     help="lr_scale multiplier applied per lr-cut action")
    return parser


def install_resilience(args, trainer, log=print):
    """Arm the graftmend layers on a built trainer per the CLI flags."""
    if not getattr(args, "no_preemption_handler", False):
        trainer.install_preemption_handler(log=log)
    if getattr(args, "breach_actions", False):
        from dalle_tpu.train.actions import BreachActions
        BreachActions(trainer, lr_cut_factor=args.lr_cut_factor,
                      log=log).attach()
        if not getattr(args, "health", False):
            log("[actions] --breach_actions without --health: the "
                "detectors see no health/* columns and will never fire")


def add_overlap_args(parser):
    """Host-overlap flags shared by every train CLI (docs/PERFORMANCE.md):
    async checkpointing, device prefetch depth, deferred metrics, and the
    rollback-snapshot placement."""
    grp = parser.add_argument_group("host overlap (docs/PERFORMANCE.md)")
    grp.add_argument("--sync_checkpointing", action="store_true",
                     help="disable async orbax saves (save() blocks until "
                          "the checkpoint is durable, the pre-PR3 behavior)")
    grp.add_argument("--device_prefetch", type=int, default=2,
                     help="batches kept device-resident ahead of the step "
                          "loop (0 disables; H2D then rides the critical "
                          "path)")
    grp.add_argument("--defer_metrics", action="store_true",
                     help="fetch step metrics one boundary late so the "
                          "device_get reads an already-finished step "
                          "(loss column lags one boundary; NaN rollback on "
                          "non-save steps triggers one boundary late)")
    grp.add_argument("--rollback_snapshot", type=str, default="auto",
                     choices=["auto", "device", "host"],
                     help="where the NaN-rollback snapshot lives (auto = "
                          "device when HBM headroom allows)")
    return parser


def overlap_train_kwargs(args) -> dict:
    """TrainConfig kwargs from add_overlap_args flags."""
    return {
        "async_checkpointing": not args.sync_checkpointing,
        "device_prefetch": args.device_prefetch,
        "defer_metrics": args.defer_metrics,
        "rollback_snapshot": args.rollback_snapshot,
    }
