#!/usr/bin/env python
"""graftir CLI — check/update the jaxpr-level program contracts.

    python scripts/ir_audit.py --check              # CI gate: fail on drift
    python scripts/ir_audit.py --update             # regenerate goldens
    python scripts/ir_audit.py --explain ENTRY      # pretty-print a contract
    python scripts/ir_audit.py --list-entries
    python scripts/ir_audit.py --check --entries train_step_dalle,serve_decode

--check rebuilds every registered entry's live contract (tracing each
program; compiling the trainer/serve entries for collectives + donation) and
diffs it against the golden under contracts/. Drift fails with a
human-readable report ("+1 all-gather 12.6 MB on axis 'fsdp'"); intentional
changes are accepted with --update (commit the JSON diff — it is the
machine-checked before/after comm+dtype story for the PR). --report writes
the report + a JSON drift dump for the CI artifact upload.

Waivers are source comments next to the code they excuse
(``# graftir: allow=donation -- why``); see docs/ANALYSIS.md.
"""

import argparse
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

# must run before jax initializes: the trainer entries trace on the 8-device
# virtual CPU mesh (same environment the test suite pins in conftest.py)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    mode = ap.add_mutually_exclusive_group()
    mode.add_argument("--check", action="store_true",
                      help="diff live contracts against goldens (default)")
    mode.add_argument("--update", action="store_true",
                      help="regenerate the golden contracts")
    mode.add_argument("--explain", metavar="ENTRY",
                      help="pretty-print one entry's live contract")
    ap.add_argument("--entries", help="comma-separated subset of entries")
    ap.add_argument("--contracts-dir",
                    default=os.path.join(ROOT, "contracts"),
                    help="golden directory (default: contracts/)")
    ap.add_argument("--format", choices=("text", "sarif"), default="text",
                    help="report output format (sarif: a SARIF 2.1.0 "
                         "document on stdout for GitHub PR annotation; "
                         "the text report moves to stderr)")
    ap.add_argument("--report", metavar="DIR",
                    help="write report.txt + drift.json + ir.sarif into "
                         "DIR (CI artifact)")
    ap.add_argument("--list-entries", action="store_true")
    args = ap.parse_args(argv)

    import jax
    jax.config.update("jax_default_matmul_precision", "float32")

    from dalle_tpu.analysis import contracts as C
    from dalle_tpu.analysis import ir_audit as A

    if args.list_entries:
        width = max(len(n) for n in C.ENTRIES)
        for name, spec in sorted(C.ENTRIES.items()):
            print(f"{name:<{width}}  {spec.source}")
        return 0

    names = sorted(C.ENTRIES)
    if args.entries:
        names = [n.strip() for n in args.entries.split(",") if n.strip()]
        unknown = [n for n in names if n not in C.ENTRIES]
        if unknown:
            sys.exit(f"ir_audit.py: unknown entries: {', '.join(unknown)} "
                     "(see --list-entries)")

    if args.explain:
        if args.explain not in C.ENTRIES:
            sys.exit(f"ir_audit.py: unknown entry {args.explain!r} "
                     "(see --list-entries)")
        spec = C.ENTRIES[args.explain]
        _, live = A.audit_entry(args.explain, spec, args.contracts_dir,
                                update=False)
        print(A.explain(live))
        return 0

    update = bool(args.update)
    # progress goes to stderr under --format sarif: stdout must stay a
    # single parseable SARIF document for `> ir.sarif` redirection
    progress_out = sys.stderr if args.format == "sarif" else sys.stdout
    reports = []
    for name in names:
        print(f"-- [{'update' if update else 'check'}] {name}", flush=True,
              file=progress_out)
        report, _ = A.audit_entry(name, C.ENTRIES[name], args.contracts_dir,
                                  update=update)
        reports.append(report)

    sources = {n: C.ENTRIES[n].source for n in names}
    scope = f"{len(names)} entr{'y' if len(names) == 1 else 'ies'}"
    text = A.render_report(reports, sources, scope)

    # drift/problem lines as SARIF findings: contracts pin whole programs,
    # so each finding anchors at the entry's source file (line 1 — there
    # is no single culprit line in a jaxpr diff)
    from dalle_tpu.analysis.core import Finding, to_sarif
    sarif_findings = []
    sarif_rules = {}
    for r in reports:
        for rule, drift_lines in sorted(r.drift.items()):
            if rule == "missing":
                continue
            rid = f"ir-drift-{rule}"
            sarif_rules[rid] = (f"graftir contract drift in the "
                                f"'{rule}' section")
            for line in drift_lines:
                sarif_findings.append(Finding(rid, sources[r.name], 1,
                                              f"{r.name}: {line}"))
        for prob in r.problems:
            sarif_rules["ir-waiver-problem"] = "malformed graftir waiver"
            sarif_findings.append(Finding("ir-waiver-problem",
                                          sources[r.name], 1,
                                          f"{r.name}: {prob}"))
    sarif = to_sarif(sarif_findings, "graftir", sarif_rules)
    if args.format == "sarif":
        print(json.dumps(sarif, indent=1))
        print(text, file=sys.stderr)
    else:
        print(text)

    if args.report:
        os.makedirs(args.report, exist_ok=True)
        with open(os.path.join(args.report, "report.txt"), "w",
                  encoding="utf-8") as fh:
            fh.write(text + "\n")
        with open(os.path.join(args.report, "drift.json"), "w",
                  encoding="utf-8") as fh:
            json.dump([{"entry": r.name, "drift": r.drift,
                        "waived": r.waived, "problems": r.problems}
                       for r in reports], fh, indent=1, sort_keys=True)
            fh.write("\n")
        with open(os.path.join(args.report, "ir.sarif"), "w",
                  encoding="utf-8") as fh:
            json.dump(sarif, fh, indent=1)
            fh.write("\n")

    # distinct exit codes so CI logs can tell the two failure classes
    # apart: 1 = real contract drift (or waiver problems) — a regression;
    # 3 = ONLY missing goldens — a new entry point that needs --update,
    # not a change in any pinned program
    drifted = [r for r in reports
               if r.problems or any(k != "missing" for k in r.drift)]
    missing = [r for r in reports if "missing" in r.drift]
    if drifted:
        return 1
    if missing:
        print(f"ir_audit: exit 3 — {len(missing)} golden(s) MISSING (new "
              "entry point?), no drift in existing contracts; run "
              "scripts/ir_audit.py --update and commit the new golden(s)")
        return 3
    return 0


if __name__ == "__main__":
    sys.exit(main())
