#!/usr/bin/env python
"""Referee the shipped tiny perceptual net against its alternatives with
judges NONE of the arms trained on (VERDICT r3 next #4).

Arms (identical small VQGANs on synthetic shapes, disc off, same data order):
  * tiny@0.22      — the shipped tiny-LPIPS at scale-matched weight (its
                     metric is ~4.5x stronger per unit weight than ones-init;
                     NEXT.md r3)
  * onesinit@1.0   — the offline ones-init fallback ('vgg' with no weights)
  * none           — no perceptual term (pixel + quant losses only)

Judges (held-out shapes, lower = better recon under that judge):
  * vgg-lpips      — the golden-imported REAL VGG16 LPIPS
                     (models/lpips.py:load_torch_weights) when
                     ``--vgg_pth``/``--lins_pth`` point at local torchvision
                     vgg16 + taming vgg.pth state dicts. This sandbox has no
                     network and ships no VGG weights, so the row prints
                     "unavailable" here — the harness is complete and runs
                     the VERDICT's exact experiment wherever the weights
                     exist.
  * judge-net      — an INDEPENDENTLY trained tiny-LPIPS (different seed,
                     different distortion draw order, trained fresh in this
                     run) — same family as the trainee but none of the arms
                     optimized against ITS weights.
  * ssim           — structural similarity (closed-form, training-free).

Usage: python scripts/eval_perceptual_judge.py [--steps 600]
       [--vgg_pth vgg16.pth --lins_pth vgg.pth]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np


def ssim(a, b, data_range=2.0):
    """Mean SSIM over NHWC batches (7x7 uniform window, standard constants)."""
    from jax import numpy as jnp

    k = jnp.ones((7, 7, 1, 1), jnp.float32) / 49.0
    k = jnp.tile(k, (1, 1, 1, a.shape[-1]))

    def filt(x):
        return jax.lax.conv_general_dilated(
            x, k, (1, 1), "VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=x.shape[-1])

    a = jnp.asarray(a, jnp.float32)
    b = jnp.asarray(b, jnp.float32)
    mu_a, mu_b = filt(a), filt(b)
    var_a = filt(a * a) - mu_a ** 2
    var_b = filt(b * b) - mu_b ** 2
    cov = filt(a * b) - mu_a * mu_b
    c1, c2 = (0.01 * data_range) ** 2, (0.03 * data_range) ** 2
    s = ((2 * mu_a * mu_b + c1) * (2 * cov + c2) /
         ((mu_a ** 2 + mu_b ** 2 + c1) * (var_a + var_b + c2)))
    return float(jnp.mean(s))


def train_arm(name, perceptual_net, weight, train_imgs, steps, batch):
    from dalle_tpu.config import (MeshConfig, OptimConfig, TrainConfig,
                                  VQGANConfig)
    from dalle_tpu.models.gan import GANLossConfig
    from dalle_tpu.train.trainer_vqgan import VQGANTrainer

    cfg = VQGANConfig(embed_dim=32, n_embed=256, z_channels=32, resolution=64,
                      ch=32, ch_mult=(1, 2, 2), num_res_blocks=1,
                      attn_resolutions=())
    tc = TrainConfig(batch_size=batch, checkpoint_dir=f"/tmp/pjudge_{name}",
                     preflight_checkpoint=False, mesh=MeshConfig(dp=1),
                     metrics_every=200, seed=0,
                     optim=OptimConfig(learning_rate=2e-4))
    lc = GANLossConfig(disc_start=10 ** 9, perceptual_weight=weight,
                       perceptual_net=perceptual_net)
    tr = VQGANTrainer(cfg, tc, loss_cfg=lc)
    rng = np.random.RandomState(0)          # same data order in every arm
    n = len(train_imgs)
    for _ in range(steps):
        tr.train_step(train_imgs[rng.randint(0, n, batch)])
    return tr


def train_judge_net(seed=12345):
    """A fresh tiny-LPIPS nobody trained against: same recipe as
    scripts/train_perceptual.py but a different seed (fresh init, fresh
    distortion draws)."""
    import jax.numpy as jnp
    from dalle_tpu.data.synthetic import ShapesDataset
    from dalle_tpu.models.lpips import LPIPS, TINY_SLICES
    from train_perceptual import (COLORS, SCALES, SHAPES, rank_accuracy,
                                  train_lins, train_trunk)

    ds = ShapesDataset(image_size=64, variants=6, seed=0)
    samples = [ds[i] for i in range(len(ds))]
    images01 = jnp.asarray(np.stack([s.image for s in samples]),
                           jnp.float32) / 255.0
    shape_ids = {s: i for i, s in enumerate(SHAPES)}
    color_ids = {c: i for i, c in enumerate(COLORS)}
    scale_ids = {s: i for i, s in enumerate(SCALES)}
    labels = (np.array([shape_ids[s.label[1]] for s in samples]),
              np.array([color_ids[s.label[0]] for s in samples]),
              np.array([scale_ids[s.label[2]] for s in samples]))
    images = images01 * 2.0 - 1.0
    trunk = train_trunk(images, labels, steps=600, batch=64, seed=seed)
    model = LPIPS(slices=TINY_SLICES)
    params = jax.device_get(model.init(jax.random.PRNGKey(seed),
                                       images[:2], images[:2]))
    params["params"]["vgg"] = jax.device_get(trunk)["params"]
    params = train_lins(model, params, images01, steps=500, batch=32,
                        seed=seed + 1)
    acc = rank_accuracy(model, params, images01, seed=seed + 2)
    print(f"judge-net held-out 2AFC: {acc:.3f}", flush=True)
    return model, params


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--steps", type=int, default=600)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--vgg_pth", type=str, default=None,
                    help="torchvision vgg16 state_dict (.pth) for the real "
                         "VGG-LPIPS judge")
    ap.add_argument("--lins_pth", type=str, default=None,
                    help="taming vgg.pth lin-head state_dict")
    args = ap.parse_args(argv)

    import jax.numpy as jnp
    from dalle_tpu.data.synthetic import ShapesDataset

    ds = ShapesDataset(image_size=64, variants=6, seed=0)
    imgs = np.stack([ds[i].image for i in range(len(ds))])
    imgs = imgs.astype(np.float32) / 127.5 - 1.0
    perm = np.random.RandomState(42).permutation(len(imgs))
    test, train = imgs[perm[:32]], imgs[perm[32:]]

    arms = [("tiny@0.22", "tiny", 0.22), ("onesinit@1.0", "vgg", 1.0),
            ("none", "none", 0.0)]
    recons = {}
    for name, net, w in arms:
        tr = train_arm(name.split("@")[0], net, w, train, args.steps,
                       args.batch)
        recons[name] = np.asarray(jax.device_get(tr.reconstruct(test)))
        print(f"arm {name}: trained {args.steps} steps", flush=True)

    judges = {}

    # real VGG-LPIPS (the VERDICT judge) — when weights are available
    if args.vgg_pth:
        import torch
        from dalle_tpu.models.lpips import init_lpips, load_torch_weights
        vgg_state = torch.load(args.vgg_pth, map_location="cpu")
        lin_state = (torch.load(args.lins_pth, map_location="cpu")
                     if args.lins_pth else {})
        model, params = init_lpips(jax.random.PRNGKey(0), image_size=64)
        params = load_torch_weights(params, vgg_state, lin_state)
        judges["vgg_lpips"] = lambda r, m=model, p=params: float(jnp.mean(
            m.apply(p, jnp.asarray(r), jnp.asarray(test))))
    else:
        print("vgg-lpips judge: unavailable (no --vgg_pth; this sandbox has "
              "no network and no local VGG weights)", flush=True)

    jm, jp = train_judge_net()
    judges["judge_net"] = lambda r: float(jnp.mean(
        jm.apply(jp, jnp.asarray(r), jnp.asarray(test))))
    judges["ssim"] = lambda r: ssim(r, test)
    judges["l1"] = lambda r: float(np.mean(np.abs(r - test)))

    table = {}
    for name in recons:
        table[name] = {j: round(f(recons[name]), 5)
                       for j, f in judges.items()}
        print(json.dumps({"arm": name, **table[name]}), flush=True)

    def best(judge, bigger_better=False):
        vals = {a: table[a][judge] for a in table}
        pick = max(vals, key=vals.get) if bigger_better else min(vals, key=vals.get)
        return pick

    verdict = {"judge_net_best": best("judge_net"),
               "ssim_best": best("ssim", bigger_better=True),
               "tiny_beats_onesinit_judge_net":
                   table["tiny@0.22"]["judge_net"]
                   < table["onesinit@1.0"]["judge_net"]}
    if "vgg_lpips" in judges:
        verdict["vgg_best"] = best("vgg_lpips")
    print(json.dumps({"metric": "perceptual_judge", **verdict}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
