#!/usr/bin/env python
"""On-chip bench sweep: try model/batch variants and report tokens/s + MFU.

Exploration harness behind bench.py (which records the single flagship line).
Run on the real chip: python scripts/bench_sweep.py small medium
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np


def run(name, cfg_kw, batch, steps=8, attn_flops=True, scan_k=0):
    """``scan_k > 0``: drive trainer.train_steps with (scan_k, b, ...) stacks
    — per-dispatch tunnel overhead (~20ms/call here) amortizes over scan_k
    device-side steps, measuring the chip rather than the host."""
    from dalle_tpu.config import DalleConfig, MeshConfig, OptimConfig, TrainConfig
    from dalle_tpu.parallel.mesh import build_mesh
    from dalle_tpu.train.metrics import device_peak_tflops
    from dalle_tpu.train.trainer_dalle import DalleTrainer

    cfg = DalleConfig(**cfg_kw)
    n_dev = jax.device_count()
    mesh = build_mesh(MeshConfig(dp=n_dev))
    train_cfg = TrainConfig(batch_size=batch, checkpoint_dir="/tmp/bench_ckpt",
                            preflight_checkpoint=False, mesh=MeshConfig(dp=n_dev),
                            metrics_every=1000,
                            optim=OptimConfig(grad_clip_norm=0.5))
    trainer = DalleTrainer(cfg, train_cfg, mesh=mesh)
    rng = np.random.RandomState(0)
    text = rng.randint(1, cfg.num_text_tokens, (batch, cfg.text_seq_len))
    image_ids = rng.randint(0, cfg.image_vocab_size, (batch, cfg.image_seq_len))

    def sync():
        jax.device_get(jax.tree.leaves(trainer.state.params)[0]).ravel()[0]

    if scan_k:
        texts = np.broadcast_to(text, (scan_k, *text.shape)).copy()
        idss = np.broadcast_to(image_ids, (scan_k, *image_ids.shape)).copy()
        calls = max(1, steps // scan_k)
        for _ in range(2):
            trainer.train_steps(texts, idss)
        sync()
        t0 = time.perf_counter()
        for _ in range(calls):
            trainer.train_steps(texts, idss)
        sync()
        dt = (time.perf_counter() - t0) / (calls * scan_k)
    else:
        for _ in range(3):
            trainer.train_step(text, image_ids)
        sync()
        t0 = time.perf_counter()
        for _ in range(steps):
            trainer.train_step(text, image_ids)
        sync()
        dt = (time.perf_counter() - t0) / steps

    n = cfg.total_seq_len
    tokens_per_step = batch * n
    tok_s_chip = tokens_per_step / dt / n_dev
    # PaLM-style model flops: 6N per token + attention 12·L·(h·dh)·n per token
    flops_tok = 6.0 * trainer.num_params
    if attn_flops:
        flops_tok += 12.0 * cfg.depth * cfg.heads * cfg.dim_head * n
    mfu = (flops_tok * tokens_per_step / dt) / (
        device_peak_tflops() * 1e12 * n_dev)
    out = {"name": name, "params_M": round(trainer.num_params / 1e6, 1),
           "batch": batch, "step_s": round(dt, 4),
           "tok_s_chip": round(tok_s_chip, 1), "mfu": round(mfu, 4)}
    print(json.dumps(out), flush=True)
    del trainer
    return out


SMALL = dict(num_text_tokens=10000, text_seq_len=256, dim=512, depth=12,
             heads=8, dim_head=64, image_size=128, image_vocab_size=8192,
             image_fmap_size=16, attn_softmax_f32=False)
MEDIUM = dict(num_text_tokens=49408, text_seq_len=256, dim=1024, depth=24,
              heads=16, dim_head=64, image_size=128, image_vocab_size=8192,
              image_fmap_size=16, attn_softmax_f32=False)
# the ROADMAP item-1 mid-size shape: 12 heads × 96d (h·d = 1152) sits
# between the measured small (h·d=512, fused +17%) and medium (h·d=1024,
# fused +22%) tier points; _bwd_bytes(513, 1152) ≈ 23.8M fits the raised
# 30M budget, so the fused merged-backward path engages without a new tier
MID12H96 = dict(num_text_tokens=10000, text_seq_len=256, dim=1152, depth=12,
                heads=12, dim_head=96, image_size=128, image_vocab_size=8192,
                image_fmap_size=16, attn_softmax_f32=False)


def main():
    which = sys.argv[1:] or ["small"]
    for w in which:
        if w == "small":
            # shipped-best small recipe (docs/PERF_SMALL.md): scanned
            # multi-step + chunked CE; the plain dispatch entry for reference
            run("small_scan8_chunk256_b64", dict(SMALL, loss_chunk=256), 64,
                steps=16, scan_k=8)
            run("small_b64", SMALL, 64)
        elif w == "small_fused":
            # r5: the fused-boundary kernel (ops/fused_attention.py) vs the
            # shipped-best dense recipe, same scan8+chunk256 harness
            run("small_fused_scan8_chunk256_b64",
                dict(SMALL, use_pallas="fused", loss_chunk=256), 64,
                steps=16, scan_k=8)
            run("small_fused_noremat_scan8_chunk256_b64",
                dict(SMALL, use_pallas="fused", use_remat=False,
                     loss_chunk=256), 64, steps=16, scan_k=8)
        elif w == "small12h96":
            # ROADMAP item 1: does the 12H/96d mid-size shape want its own
            # fused tier entry? Run on-chip and compare: a tier entry is
            # added ONLY where fused beats the dense recipe here (the
            # flagship d=128 precedent: measured parity → dense stays)
            run("mid12h96_scan8_chunk256_b32", dict(MID12H96, loss_chunk=256),
                32, steps=16, scan_k=8)
            run("mid12h96_fused_scan8_chunk256_b32",
                dict(MID12H96, use_pallas="fused", loss_chunk=256), 32,
                steps=16, scan_k=8)
            run("mid12h96_fused_noremat_scan8_chunk256_b32",
                dict(MID12H96, use_pallas="fused", use_remat=False,
                     loss_chunk=256), 32, steps=16, scan_k=8)
        elif w == "small128":
            run("small_b128", SMALL, 128)
        elif w == "small_opt":
            # the MFU-attack grid for the small config (VERDICT r2 next #4):
            # remat off (memory is plentiful at 50M params — stop paying the
            # recompute), flash at seq 512, and the scanned multi-step that
            # takes per-dispatch tunnel overhead out of the measurement
            run("small_b64", SMALL, 64)
            run("small_noremat_b64", dict(SMALL, use_remat=False), 64)
            run("small_flash_b64", dict(SMALL, use_pallas="on"), 64)
            run("small_noremat_flash_b64",
                dict(SMALL, use_remat=False, use_pallas="on"), 64)
            run("small_scan8_b64", SMALL, 64, steps=16, scan_k=8)
            run("small_noremat_scan8_b64", dict(SMALL, use_remat=False), 64,
                steps=16, scan_k=8)
        elif w == "small_opt2":
            # round 2: chunked vocab-head CE (the head is 23.5ms vs a 9.6ms
            # roofline at b64 — f32 logits traffic) and batch scaling
            run("small_chunk128_scan8_b64", dict(SMALL, loss_chunk=128), 64,
                steps=16, scan_k=8)
            run("small_chunk256_scan8_b64", dict(SMALL, loss_chunk=256), 64,
                steps=16, scan_k=8)
            run("small_scan8_b128", SMALL, 128, steps=16, scan_k=8)
            run("small_chunk256_scan8_b128", dict(SMALL, loss_chunk=256), 128,
                steps=16, scan_k=8)
            run("small_chunk256_scan4_b256", dict(SMALL, loss_chunk=256), 256,
                steps=8, scan_k=4)
        elif w == "medium":
            for b in (16, 32):
                run(f"medium_b{b}", MEDIUM, b)
        elif w == "medium64":
            run("medium_b64", MEDIUM, 64)
        elif w == "big":
            BIG = dict(MEDIUM, dim=2048, depth=24, heads=16, dim_head=128)
            run("big_b16", BIG, 16)
        elif w == "longseq":
            # long-sequence regime (4096 image tokens — the reference's
            # "2048 visual tokens" anecdote class, README:32-34): sparse
            # attention interleave; pallas flash + block skipping vs dense
            LS = dict(num_text_tokens=10000, text_seq_len=256, dim=512,
                      depth=4, heads=8, dim_head=64, image_size=512,
                      image_vocab_size=8192, image_fmap_size=64,
                      attn_types=("full", "axial_row", "axial_col", "full"),
                      attn_softmax_f32=False)
            # the DEFAULT config (use_pallas="auto") self-selects flash at
            # seq 4352 ≥ the 2048 crossover — no flag needed
            run("longseq_dense_b2", dict(LS, use_pallas="off"), 2, steps=4)
            run("longseq_auto_pallas_b2", LS, 2, steps=4)
        elif w == "longseq8k":
            # 8k-class sequence (90x90 fmap → 8100 image + 256 text tokens):
            # the regime where the flash kernel's O(n) memory and block
            # skipping compound (VERDICT r2 next #1 bench criterion)
            LS8 = dict(num_text_tokens=10000, text_seq_len=256, dim=512,
                       depth=4, heads=8, dim_head=64, image_size=720,
                       image_vocab_size=8192, image_fmap_size=90,
                       attn_types=("full", "axial_row", "axial_col", "full"),
                       attn_softmax_f32=False)
            run("longseq8k_dense_b1", dict(LS8, use_pallas="off"), 1, steps=3)
            run("longseq8k_auto_pallas_b1", LS8, 1, steps=3)
        elif w == "gen":
            bench_generation()
        elif w == "vae":
            bench_dvae()
        else:
            print(f"unknown config {w}", file=sys.stderr)


def bench_dvae(batch=64, steps=8):
    """dVAE training throughput, BASELINE config-1-shaped: 8192-codebook,
    128x128 images. Reports imgs/sec/chip."""
    import jax.numpy as jnp
    from dalle_tpu.config import (AnnealConfig, DVAEConfig, MeshConfig,
                                  OptimConfig, TrainConfig)
    from dalle_tpu.parallel.mesh import build_mesh
    from dalle_tpu.train.trainer_vae import VAETrainer

    cfg = DVAEConfig(image_size=128, num_tokens=8192, codebook_dim=512,
                     num_layers=3, num_resnet_blocks=1, hidden_dim=64)
    n_dev = jax.device_count()
    tc = TrainConfig(batch_size=batch, checkpoint_dir="/tmp/bench_vae_ckpt",
                     preflight_checkpoint=False, mesh=MeshConfig(dp=n_dev),
                     metrics_every=1000, optim=OptimConfig(learning_rate=1e-3))
    trainer = VAETrainer(cfg, tc, AnnealConfig(),
                         mesh=build_mesh(MeshConfig(dp=n_dev)))
    from dalle_tpu.parallel import shard_batch
    rng = np.random.RandomState(0)
    # pre-place the batch: pushing 12MB of pixels through the device tunnel
    # per step would swamp the compute being measured (a real input pipeline
    # overlaps the transfer)
    imgs = shard_batch(trainer.mesh,
                       rng.rand(batch, 128, 128, 3).astype(np.float32))
    key = jax.random.PRNGKey(0)

    def sync():
        jax.device_get(jax.tree.leaves(trainer.state.params)[0]).ravel()[0]

    for _ in range(3):
        trainer.state, _ = trainer.step_fn(trainer.state, imgs, key,
                                           jnp.float32(1.0))
    sync()
    t0 = time.perf_counter()
    for _ in range(steps):
        trainer.state, _ = trainer.step_fn(trainer.state, imgs, key,
                                           jnp.float32(1.0))
    sync()
    dt = (time.perf_counter() - t0) / steps
    print(json.dumps({"name": f"dvae_train_b{batch}", "step_s": round(dt, 4),
                      "imgs_per_sec_per_chip": round(batch / dt / n_dev, 1)}),
          flush=True)


def bench_generation(batch=64, reps=3):
    """Generation p50 latency, BASELINE config-5-shaped: DALL·E-small, 256
    image tokens, batch 64, top-k 0.9; f32 vs bf16 vs bf16+int8-KV decode
    (the int8 cache halves the cache-read bandwidth that dominates batched
    decode)."""
    import jax.numpy as jnp
    from dalle_tpu.config import DalleConfig
    from dalle_tpu.models.dalle import DALLE, init_dalle
    from dalle_tpu.ops.quantize_weights import quantize_params_int8
    from dalle_tpu.train.train_state import cast_floating

    cfg = DalleConfig(**SMALL)
    model, params = init_dalle(cfg, jax.random.PRNGKey(0))
    text = np.zeros((batch, cfg.text_seq_len), np.int32)
    text[:, :4] = 7
    bf16 = cast_floating(params, jnp.bfloat16)

    for precision in ("float32", "bfloat16", "bf16_int8kv", "int8w",
                      "int8kv_fast_topk"):
        p = {"float32": params, "bfloat16": bf16, "bf16_int8kv": bf16,
             "int8w": None, "int8kv_fast_topk": bf16}[precision]
        if p is None:
            p = quantize_params_int8(params)   # int8 kernels, bf16 elsewhere
        cache_dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16,
                       "bf16_int8kv": jnp.int8, "int8w": jnp.int8,
                       "int8kv_fast_topk": jnp.int8}[precision]
        approx = precision == "int8kv_fast_topk"

        @jax.jit
        def gen(p, text, key):
            return model.apply(p, text, key, filter_thres=0.9,
                               cache_dtype=cache_dtype, topk_approx=approx,
                               method=DALLE.generate_images_tokens)

        ids = gen(p, text, jax.random.PRNGKey(0))
        np.asarray(jax.device_get(ids[0, :1]))  # sync
        times = []
        for r in range(reps):
            t0 = time.perf_counter()
            ids = gen(p, text, jax.random.PRNGKey(r))
            np.asarray(jax.device_get(ids[0, :1]))
            times.append(time.perf_counter() - t0)
        p50 = sorted(times)[len(times) // 2]
        print(json.dumps({
            "name": f"gen_b{batch}_{precision}", "p50_s": round(p50, 4),
            "tokens_per_sec": round(batch * cfg.image_seq_len / p50, 1),
            "unique_ids": int(len(np.unique(np.asarray(ids)))),
        }), flush=True)


if __name__ == "__main__":
    main()
