#!/usr/bin/env python
"""Speculative-decode referee + timing on a TRAINED model (VERDICT r4 #4).

Trains the rainbow pipeline at DALL·E-small-ish decode shape (256 image
tokens), then measures batched generation at b64:

  * sequential `generate_images_tokens` (the shipped fast path:
    bf16 + int8 KV + fast top-k) — the baseline the bench records;
  * `generate_images_tokens_speculative` at gamma=0 (pure sequential under
    the per-(step,row) key discipline — isolates the window machinery's
    overhead) and gamma>0 with both drafts ("row" = token one grid-row
    above, "repeat" = last token);
  * token-exactness: gamma>0 output must equal gamma=0 EXACTLY (the
    acceptance machinery may never bias sampling), plus token accuracy vs
    the dVAE codes for every mode;
  * acceptance: rounds used / mean committed per round.

Reference bar: the strictly sequential generate_images loop
(dalle_pytorch/dalle_pytorch.py:523-546). Run on TPU (numbers → NEXT.md):
    python scripts/eval_speculative.py
CPU smoke: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu python scripts/eval_speculative.py --small
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from eval_decode_precisions import train_rainbow  # noqa: E402


class TexturedShapes:
    """Natural-image-like proxy corpus: the rainbow shapes with heavy
    per-pixel noise texture and a smooth random background gradient.

    The flat-color shapes corpus gives the dVAE long runs of IDENTICAL
    codebook tokens — the best case for the 'row'/'repeat' drafts. Real
    photos have textured, spatially-decorrelated token fields; this proxy
    reproduces that property (adjacent grid cells encode to different
    codes) while keeping the caption→image mapping learnable, so the
    measured acceptance bounds what a natural-image dVAE would give rather
    than inheriting the shapes corpus's optimism (ROADMAP open item 2).
    """

    def __init__(self, base, noise: float = 40.0, seed: int = 0):
        self.base = base
        self.noise = noise
        self.seed = seed
        self.image_size = base.image_size

    def __len__(self):
        return len(self.base)

    def __getitem__(self, i):
        import numpy as np
        s = self.base[i]
        rng = np.random.RandomState(self.seed * 77003 + i)
        img = s.image.astype(np.float32)
        size = img.shape[0]
        # smooth random background gradient where the render is black
        gx, gy = np.meshgrid(np.linspace(0, 1, size), np.linspace(0, 1, size))
        base_col = rng.uniform(20, 120, (3,))
        grad_col = rng.uniform(-60, 60, (3,))
        bg = base_col[None, None] + gx[..., None] * grad_col[None, None]
        dark = (img.sum(axis=-1, keepdims=True) < 30).astype(np.float32)
        img = img * (1 - dark) + bg * dark
        # per-pixel texture noise over everything
        img = img + rng.uniform(-self.noise, self.noise, img.shape)
        img = np.clip(img, 0, 255).astype(np.uint8)
        return type(s)(img, s.caption, s.label)


def _p50(fn, reps):
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    times.sort()
    return times[len(times) // 2]


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--image_size", type=int, default=64,
                    help="64px + 2 dVAE layers -> fmap 16 = 256 image tokens"
                         " (the bench_generation decode shape)")
    ap.add_argument("--num_tokens", type=int, default=64)
    ap.add_argument("--vae_steps", type=int, default=500)
    ap.add_argument("--dalle_steps", type=int, default=800)
    ap.add_argument("--batch_size", type=int, default=32)
    ap.add_argument("--train_frac", type=float, default=1.0,
                    help="train on everything: the referee cares about a "
                         "REALISTIC trained model's acceptance, not split "
                         "generalization (that's the rainbow example)")
    ap.add_argument("--dim", type=int, default=256)
    ap.add_argument("--depth", type=int, default=8)
    ap.add_argument("--eval_b", type=int, default=64)
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--temperature", type=float, default=0.5)
    ap.add_argument("--pad_text_to", type=int, default=64)
    ap.add_argument("--gammas", type=str, default="2,4,7")
    ap.add_argument("--corpus", type=str, default="rainbow",
                    choices=("rainbow", "textured"),
                    help="'textured' = the natural-image-like proxy "
                         "(noise-textured shapes over gradient "
                         "backgrounds: spatially decorrelated dVAE codes; "
                         "ROADMAP open item 2)")
    ap.add_argument("--texture_noise", type=float, default=40.0)
    ap.add_argument("--outdir", type=str, default="/tmp/eval_spec")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--small", action="store_true")
    args = ap.parse_args(argv)
    if args.small:
        args.image_size, args.num_tokens = 16, 32
        args.vae_steps, args.dalle_steps = 200, 300
        args.dim, args.depth, args.eval_b = 64, 2, 8
        args.reps, args.pad_text_to = 2, 8
        args.gammas = "2,3"

    import jax
    import jax.numpy as jnp
    import numpy as np
    from dalle_tpu.models.dalle import DALLE
    from dalle_tpu.train.train_state import cast_floating

    dataset = None
    if args.corpus == "textured":
        from dalle_tpu.data.synthetic import ShapesDataset
        dataset = TexturedShapes(ShapesDataset(image_size=args.image_size),
                                 noise=args.texture_noise, seed=args.seed)
    model, params, text, codes, tr_idx = train_rainbow(args, dataset=dataset)
    n_img = codes.shape[1]
    sel = tr_idx[: args.eval_b]
    # tile up to the eval batch if the dataset is smaller
    while len(sel) < args.eval_b:
        sel = np.concatenate([sel, tr_idx[: args.eval_b - len(sel)]])
    t = jnp.asarray(text[sel])
    key = jax.random.PRNGKey(1)
    bf16 = cast_floating(params, jnp.bfloat16)
    rows = []

    # -- shipped sequential fast path (bench baseline) ----------------------
    seq_gen = jax.jit(lambda p, t, k: model.apply(
        p, t, k, filter_thres=0.9, temperature=args.temperature,
        cache_dtype=jnp.int8, topk_approx=True,
        method=DALLE.generate_images_tokens))
    ids_seq = np.asarray(seq_gen(bf16, t, key))
    acc_seq = float((ids_seq == codes[sel]).mean())
    p50 = _p50(lambda: np.asarray(jax.device_get(
        seq_gen(bf16, t, key)[0, :1])), args.reps)
    rows.append({"mode": "sequential_int8kv_fast_topk", "p50_s": round(p50, 4),
                 "token_acc": round(acc_seq, 4)})
    print(rows[-1], flush=True)

    # -- speculative at gamma=0 then the draft grid -------------------------
    base_ids = None
    for gamma, draft in [(0, "repeat")] + [
            (int(g), d) for g in args.gammas.split(",")
            for d in ("row", "repeat")]:
        spec_gen = jax.jit(lambda p, t, k, g=gamma, d=draft: model.apply(
            p, t, k, gamma=g, draft=d, filter_thres=0.9,
            temperature=args.temperature, cache_dtype=jnp.int8,
            topk_approx=True, return_stats=True,
            method=DALLE.generate_images_tokens_speculative))
        ids, rounds, committed = spec_gen(bf16, t, key)
        ids = np.asarray(ids)
        rounds = int(rounds)
        acc = float((ids == codes[sel]).mean())
        if gamma == 0:
            base_ids = ids
            exact = 1.0
        else:
            exact = float((ids == base_ids).mean())
        p50 = _p50(lambda: np.asarray(jax.device_get(
            spec_gen(bf16, t, key)[0][0, :1])), args.reps)
        row = {"mode": f"spec_g{gamma}_{draft}" if gamma else "spec_g0",
               "p50_s": round(p50, 4), "token_acc": round(acc, 4),
               "rounds": rounds,
               "committed_per_round": round(args.eval_b * n_img / max(
                   rounds, 1) / args.eval_b, 2),
               "exact_vs_g0": round(exact, 4)}
        rows.append(row)
        print(row, flush=True)
        if gamma == 0:
            continue
        assert exact == 1.0, (
            f"speculative gamma={gamma} draft={draft} output diverged from "
            f"gamma=0: {exact:.4f} — the acceptance machinery is biased")

    print(json.dumps({"metric": "speculative_decode_referee", "rows": rows,
                      "batch": int(args.eval_b),
                      "image_seq_len": int(n_img)}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
