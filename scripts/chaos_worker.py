#!/usr/bin/env python
"""One member of a graftmend chaos/elastic pod (docs/RESILIENCE.md).

Spawned by ``scripts/chaos_smoke.py``'s :class:`ElasticAgent` (or run by
hand for debugging): installs the chaos FaultPlan from the env, joins the
pod's current membership epoch over the real gloo/DCN path
(``jax.distributed.initialize`` through the retried backend connect),
trains a tiny dVAE with deterministic per-step synthetic batches, heartbeats
every step, restores from the last durable checkpoint on (re)start, and on
completion writes a digest artifact — the sha256 over the raw bytes of
every (params, opt_state) leaf — that the smoke compares BITWISE against an
uninterrupted reference run at the same step.

Exit protocol (what the agent keys on):
  * 0  — reached the target step; digest written.
  * 77 (``EXIT_RECONFIGURE``) — preempted (SIGTERM graceful save landed)
    or a peer died: respawn me into the next epoch.
  * anything else — crash (the agent reconfigures per policy).

Determinism contract: the batch for host step s is
``RandomState(seed + s)``, and every rng draw in the trainer folds off the
host step — so re-executing [restore-step, crash-step] after recovery
reproduces the exact bits of a run that never crashed.
"""

import argparse
import hashlib
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_batch(seed: int, step: int, batch: int, size: int):
    import numpy as np
    rng = np.random.RandomState(seed + step)
    return (rng.rand(batch, size, size, 3).astype(np.float32),)


def state_digest(state) -> str:
    """sha256 over every (params, opt_state) leaf's raw bytes, in
    deterministic tree order — the bitwise-resume oracle."""
    import jax
    import numpy as np
    h = hashlib.sha256()
    for leaf in jax.tree.leaves((state.params, state.opt_state)):
        h.update(np.ascontiguousarray(jax.device_get(leaf)).tobytes())
    return h.hexdigest()


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--run_dir", required=True,
                    help="shared pod dir (epoch file, heartbeats, ckpt)")
    ap.add_argument("--target_steps", type=int, default=8)
    ap.add_argument("--save_every", type=int, default=2,
                    help="0 = never save (reference legs)")
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--restore_step", type=int, default=None,
                    help="pin the restore step (reference legs); default: "
                    "resume from latest durable if any")
    ap.add_argument("--reference", action="store_true",
                    help="reference leg: no elastic runtime, no heartbeats")
    ap.add_argument("--peer_timeout_s", type=float, default=0.0)
    ap.add_argument("--health_page", action="store_true",
                    help="arm the graftpulse health taps + sentry and wire "
                    "breaches into the heartbeat page marker "
                    "(degrade.install_breach_pager) — the agent's "
                    "DegradeMonitor then drains this worker on a breach")
    ap.add_argument("--sync_ckpt", action="store_true",
                    help="synchronous checkpointing: every save is durable "
                    "at its boundary (scenarios that script against the "
                    "newest-durable-step need this determinism; the default "
                    "async path is the production config)")
    ap.add_argument("--compile_cache", default="",
                    help="persistent XLA compile cache dir (shared across "
                    "the pod; makes a rejoin near-zero-compile)")
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from dalle_tpu import chaos, obs
    obs.configure()
    chaos.install_from_env()

    from dalle_tpu.config import (AnnealConfig, DVAEConfig, MeshConfig,
                                  ObsConfig, TrainConfig)
    from dalle_tpu.parallel import backend as B
    from dalle_tpu.parallel import elastic
    from dalle_tpu.train.trainer_vae import VAETrainer
    from dalle_tpu.utils.misc import enable_compilation_cache

    if args.compile_cache:
        enable_compilation_cache(args.compile_cache)

    worker = None
    if not args.reference and elastic.DIR_ENV in os.environ:
        worker = elastic.ElasticWorker.from_env(
            hb_interval_s=0.1, peer_timeout_s=args.peer_timeout_s)
        # start NOW: the beater covers the long no-step phases (backend
        # dial-in, restore, first-step compile) so liveness readers see a
        # fresh-but-not-yet-stepping worker, not a corpse
        worker.start()
        ep = worker.epoch
        pid = ep.process_id(worker.worker_id)
        if pid is None:
            print(f"worker {worker.worker_id}: not a member of epoch "
                  f"{ep.epoch}; exiting")
            return 0
        ns = argparse.Namespace(
            distributed_backend="jax",
            coordinator_address=ep.coordinator_address if ep.nproc > 1
            else None,
            num_processes=ep.nproc if ep.nproc > 1 else None,
            process_id=pid)
    else:
        ns = argparse.Namespace(distributed_backend="jax",
                                coordinator_address=None,
                                num_processes=None, process_id=None)
    backend = B.set_backend_from_args(ns).initialize(MeshConfig())

    model_cfg = DVAEConfig(image_size=16, num_tokens=16, codebook_dim=8,
                           num_layers=1, num_resnet_blocks=0, hidden_dim=8)
    tc = TrainConfig(
        batch_size=args.batch, seed=args.seed, log_every=1,
        save_every_steps=args.save_every or 0,
        keep_n_checkpoints=None,           # fallback needs older steps
        checkpoint_dir=os.path.join(args.run_dir, "ckpt"),
        preflight_checkpoint=False,
        async_checkpointing=not args.sync_ckpt,
        device_prefetch=0,                 # resume math owns the iterator
        obs=ObsConfig(health=True) if args.health_page else ObsConfig(),
        mesh=MeshConfig())
    trainer = VAETrainer(model_cfg, tc, anneal_cfg=AnnealConfig(),
                         backend=backend)
    if worker is not None and args.health_page:
        # graftward drain-on-health-page: build the sentry PRE-fit (the
        # BreachActions.attach precedent — fit's is-None check then reuses
        # it) and chain its on_breach into the heartbeat page marker
        from dalle_tpu.degrade import install_breach_pager
        from dalle_tpu.obs.anomaly import HealthSentry
        if trainer.health_sentry is None:
            trainer.health_sentry = HealthSentry.from_obs_config(tc.obs)
        install_breach_pager(worker, trainer.health_sentry)

    restored_from = None
    if args.restore_step is not None:
        trainer.restore(args.restore_step)
        restored_from = args.restore_step
    elif trainer.ckpt.latest_step() is not None:
        trainer.restore()
        restored_from = int(trainer._host_step)
    print(f"worker: world={backend.get_world_size()} "
          f"proc={os.getpid()} start_step={trainer._host_step} "
          f"restored_from={restored_from}")

    def leave_pod():
        """Exit discipline: BARRIER, then detach from the coordination
        service. Without this, the first worker to exit kills its peers —
        the coordination service declares it dead and fatally terminates
        every other member, and a peer mid-collective can even read
        garbage instead of erroring. Symmetric exits (everyone done, or
        everyone preempted at the same boundary) meet at the barrier;
        asymmetric deaths are the agent's job, not ours."""
        try:
            backend.local_barrier()
            import jax
            if backend.get_world_size() > 1:
                jax.distributed.shutdown()
        except Exception as exc:  # noqa: BLE001 - a broken pod (peer died
            # while we drained) cannot barrier; the agent handles it
            print(f"worker: leave_pod best-effort failed: {exc!r}")

    trainer.install_preemption_handler()

    batches = (make_batch(args.seed, s, args.batch, model_cfg.image_size)
               for s in range(trainer._host_step, args.target_steps))
    on_step = writer = None
    if worker is not None:
        # graftward straggler signal: forward the grafttrace step
        # breakdown's device/collective wait (t_dispatch + t_sync) into
        # the heartbeat — in lockstep SPMD the worker that never waits IS
        # the straggler (degrade/detector.py). The writer sees step s's
        # record after on_step(s) fired, so beats carry the previous
        # step's wait; one step stale, which the detector's EWMA absorbs.
        last_m: dict = {}

        class _HBWriter:
            def log(self, step, m):
                last_m.clear()
                last_m.update(m)
        writer = _HBWriter()

        def on_step(step):
            blocked = (last_m.get("t_dispatch_s", 0.0)
                       + last_m.get("t_sync_s", 0.0)
                       if "t_dispatch_s" in last_m else None)
            worker.on_step(step, blocked_s=blocked)
    trainer.fit(batches, steps=args.target_steps,
                metrics_writer=writer, on_step=on_step)
    if worker is not None:
        worker.stop()

    if trainer.preempted and trainer._host_step < args.target_steps:
        # graceful preemption before the budget: state is durable — ask
        # the agent to respawn us into the next epoch. Real preemption
        # SIGTERMs every host at once, so the whole gang passes through
        # here together and the exit barrier is symmetric.
        print(f"worker: preempted at step {trainer._host_step}; requesting "
              "reconfiguration")
        leave_pod()
        return elastic.EXIT_RECONFIGURE

    snap = obs.metrics_snapshot()
    artifact = {
        "worker_id": worker.worker_id if worker is not None else -1,
        "epoch": worker.epoch.epoch if worker is not None else -1,
        "step": int(trainer._host_step),
        "world_size": int(backend.get_world_size()),
        "restored_from": restored_from,
        "digest": state_digest(trainer.state),
        "counters": {k: v for k, v in snap.items()
                     if k.startswith(("retry.", "chaos.", "ckpt.",
                                      "elastic."))},
    }
    tag = (f"w{artifact['worker_id']}" if worker is not None else "ref")
    out = os.path.join(args.run_dir, f"digest_{tag}.json")
    with open(out, "w", encoding="utf-8") as fh:
        json.dump(artifact, fh, indent=2)
    print(f"worker: done at step {artifact['step']} "
          f"digest={artifact['digest'][:16]}… → {out}")
    leave_pod()
    return 0


if __name__ == "__main__":
    sys.exit(main())
