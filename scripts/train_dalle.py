#!/usr/bin/env python
"""Train DALL·E on TPU (or the CPU mesh).

Reference: legacy/train_dalle.py (SURVEY.md §3.1): tokenizer selection, the
VAE precedence chain, folder/WebDataset data, resume, checkpoint rotation,
periodic in-training sampling. One process per host; data parallelism comes
from the mesh.

Examples:
  python scripts/sampler.py --outdir /tmp/shapes --count 256 --image_size 64
  python scripts/train_dalle.py --image_text_folder /tmp/shapes \
      --untrained_vae --image_size 64 --dim 128 --depth 2 --epochs 1 \
      --batch_size 8 --text_seq_len 32
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import (add_compile_cache_args, add_health_args,  # noqa: E402
                     add_resilience_args, install_resilience,
                     add_overlap_args, add_profiler_args, add_vae_args,
                     build_vae_from_args, enable_compile_cache,
                     health_obs_kwargs, install_health_recorder,
                     install_sigusr2_profiler, overlap_train_kwargs,
                     save_image_grid, save_vae_sidecar)


def build_parser():
    ap = argparse.ArgumentParser(description=__doc__)
    data = ap.add_argument_group("data")
    data.add_argument("--image_text_folder", type=str, default=None,
                      help="folder pairing images with .txt captions "
                           "(or filename captions via --text_from_filename)")
    data.add_argument("--wds", type=str, default=None,
                      help="tar shard spec: dir, glob, brace range, or pipe:")
    data.add_argument("--synthetic", action="store_true")
    data.add_argument("--text_from_filename", action="store_true")
    data.add_argument("--image_size", type=int, default=128)

    tok = ap.add_argument_group("tokenizer")
    tok.add_argument("--tokenizer", type=str, default="simple",
                     choices=["simple", "yttm", "hug", "chinese"])
    tok.add_argument("--bpe_path", type=str, default=None)

    model = ap.add_argument_group("model")
    model.add_argument("--dim", type=int, default=512)
    model.add_argument("--depth", type=int, default=2)
    model.add_argument("--heads", type=int, default=8)
    model.add_argument("--dim_head", type=int, default=64)
    model.add_argument("--text_seq_len", type=int, default=256)
    model.add_argument("--num_text_tokens", type=int, default=None,
                       help="default: tokenizer vocab size")
    model.add_argument("--attn_types", type=str, default="full",
                       help="comma list: full,axial_row,axial_col,conv_like,sparse")
    model.add_argument("--reversible", action="store_true")
    model.add_argument("--stable", action="store_true")
    model.add_argument("--shift_tokens", action="store_true")
    model.add_argument("--no_rotary", action="store_true")
    model.add_argument("--loss_img_weight", type=float, default=7.0)
    model.add_argument("--attn_dropout", type=float, default=0.0)
    model.add_argument("--ff_dropout", type=float, default=0.0)
    add_vae_args(ap)

    train = ap.add_argument_group("training")
    train.add_argument("--epochs", type=int, default=20)
    train.add_argument("--batch_size", type=int, default=16)
    train.add_argument("--learning_rate", type=float, default=3e-4)
    train.add_argument("--clip_grad_norm", type=float, default=0.5)
    train.add_argument("--ga_steps", type=int, default=1)
    train.add_argument("--null_cond_prob", type=float, default=0.0)
    train.add_argument("--output_dir", type=str, default="./dalle_ckpt")
    train.add_argument("--save_every_n_steps", type=int, default=1000)
    train.add_argument("--keep_n_checkpoints", type=int, default=None)
    train.add_argument("--sample_every_steps", type=int, default=0)
    train.add_argument("--sample_dir", type=str, default="./dalle_samples")
    train.add_argument("--resume", action="store_true")
    train.add_argument("--seed", type=int, default=42)
    train.add_argument("--lr_scheduler", type=str, default="constant",
                       choices=["constant", "cosine", "exponential", "plateau"],
                       help="plateau = ReduceLROnPlateau parity (ref :444-459)")
    train.add_argument("--wandb", action="store_true",
                       help="mirror metrics/images/artifacts to wandb "
                            "(ref legacy/train_dalle.py:463-476)")
    train.add_argument("--wandb_project", type=str, default="dalle_train_transformer")
    train.add_argument("--wandb_name", type=str, default=None)
    train.add_argument("--log_artifacts", action="store_true",
                       help="upload each checkpoint as a wandb artifact (ref :667-669)")
    train.add_argument("--steps", type=int, default=None)
    train.add_argument("--scan_steps", type=int, default=1,
                       help="k optimizer steps per device dispatch "
                            "(lax.scan over stacked microbatches; host "
                            "events move to k-step granularity; a NaN "
                            "rollback rewinds the whole k-step group)")
    train.add_argument("--no_preflight", action="store_true")
    train.add_argument("--flops_profiler", action="store_true",
                       help="profile at step 200 then exit (ref :492-499)")

    add_overlap_args(ap)
    add_health_args(ap)
    add_resilience_args(ap)
    add_compile_cache_args(ap)
    add_profiler_args(ap)

    tel = ap.add_argument_group("telemetry (grafttrace, docs/OBSERVABILITY.md)")
    tel.add_argument("--trace", action="store_true",
                     help="collect spans; exports <output_dir>/obs/"
                          "{trace.json,spans.jsonl} (Perfetto / obs_report)")
    tel.add_argument("--watchdog_deadline_s", type=float, default=0.0,
                     help="stall report if no step completes within this "
                          "many seconds (0 = off; set above worst expected "
                          "compile, e.g. 600 on pods)")
    tel.add_argument("--prometheus_path", type=str, default="",
                     help="node-exporter textfile target for live gauges")

    from dalle_tpu.parallel import wrap_arg_parser
    wrap_arg_parser(ap)
    return ap


def main(argv=None):
    args = build_parser().parse_args(argv)
    if not (args.image_text_folder or args.wds or args.synthetic):
        print("error: provide --image_text_folder, --wds or --synthetic",
              file=sys.stderr)
        return 2

    enable_compile_cache(args)
    install_sigusr2_profiler(os.path.join(args.output_dir, "profile"),
                             args)
    import numpy as np
    from dalle_tpu.config import DalleConfig, ObsConfig, OptimConfig, TrainConfig
    from dalle_tpu.models.wrapper import DalleWithVae, dalle_config_for_vae
    from dalle_tpu.parallel import set_backend_from_args
    from dalle_tpu.text.tokenizer import get_tokenizer
    from dalle_tpu.train.trainer_dalle import DalleTrainer

    backend = set_backend_from_args(args).initialize()
    backend.check_batch_size(args.batch_size)
    is_root = backend.is_root_worker()

    tok_kw = {"bpe_path": args.bpe_path} if args.bpe_path else {}
    tokenizer = get_tokenizer(args.tokenizer, **tok_kw)
    vae = build_vae_from_args(args, backend)
    assert vae.image_size == args.image_size, (
        f"--image_size {args.image_size} != vae.image_size {vae.image_size}")

    num_text_tokens = args.num_text_tokens or max(tokenizer.vocab_size, 256)
    if num_text_tokens < tokenizer.vocab_size:
        print(f"error: --num_text_tokens {num_text_tokens} < tokenizer vocab "
              f"{tokenizer.vocab_size} (ids would index out of range)",
              file=sys.stderr)
        return 2
    model_cfg = dalle_config_for_vae(
        vae, num_text_tokens=num_text_tokens, text_seq_len=args.text_seq_len,
        dim=args.dim, depth=args.depth, heads=args.heads,
        dim_head=args.dim_head, attn_types=tuple(args.attn_types.split(",")),
        reversible=args.reversible, stable=args.stable,
        shift_tokens=args.shift_tokens, rotary_emb=not args.no_rotary,
        loss_img_weight=args.loss_img_weight, attn_dropout=args.attn_dropout,
        ff_dropout=args.ff_dropout)
    train_cfg = TrainConfig(
        runtime_lr_scale=args.breach_actions,
        batch_size=args.batch_size, epochs=args.epochs, seed=args.seed,
        checkpoint_dir=args.output_dir,
        save_every_steps=args.save_every_n_steps,
        keep_n_checkpoints=args.keep_n_checkpoints,
        preflight_checkpoint=not args.no_preflight,
        sample_every_steps=args.sample_every_steps,
        profile_step=200 if args.flops_profiler else 0,
        log_artifacts=args.log_artifacts, scan_steps=args.scan_steps,
        **overlap_train_kwargs(args),
        optim=OptimConfig(learning_rate=args.learning_rate,
                          grad_clip_norm=args.clip_grad_norm,
                          grad_accum_steps=args.ga_steps,
                          lr_scheduler=args.lr_scheduler),
        obs=ObsConfig(trace=args.trace,
                      watchdog_deadline_s=args.watchdog_deadline_s,
                      prometheus_path=args.prometheus_path,
                      **health_obs_kwargs(args)))
    install_health_recorder(args, os.path.join(args.output_dir,
                                               "health_bundles"))

    trainer = DalleTrainer(model_cfg, train_cfg, backend=backend,
                           null_cond_prob=args.null_cond_prob)
    trainer.extra_meta = {
        "vae_class_name": type(vae).__name__,
        "vae_hparams": getattr(getattr(vae, "model", None), "cfg", None)
        and vae.model.cfg.to_dict()}
    if is_root:
        save_vae_sidecar(args.output_dir, vae)
    if args.resume:
        meta = trainer.restore()
        if is_root:
            print(f"resumed at step {trainer._host_step}"
                  f" (ckpt model_class={meta and meta.get('model_class')})")

    # -- data → (text ids, image ids) batches ------------------------------
    def encode_batch(images, captions):
        text = tokenizer.tokenize(list(captions), args.text_seq_len,
                                  truncate_text=True)
        ids = np.asarray(vae.get_codebook_indices(np.asarray(images)))
        return text, ids

    if args.synthetic:
        from dalle_tpu.data.synthetic import ShapesDataset, batch_iterator
        ds = ShapesDataset(image_size=args.image_size)
        raw = batch_iterator(ds, args.batch_size, seed=args.seed,
                             epochs=args.epochs)
        batches = (encode_batch(imgs, caps) for imgs, caps in raw)
    elif args.wds:
        from dalle_tpu.data.webdataset import WebDataset
        wds = (WebDataset(args.wds, shuffle_shards=True, repeat=args.epochs,
                          seed=args.seed)
               .decode(image_size=args.image_size)
               .map(lambda s: (next(s[k] for k in ("jpg", "jpeg", "png")
                                    if k in s),
                               next(s[k] for k in ("txt", "text", "caption")
                                    if k in s)))
               .shuffle(256)
               .batched(args.batch_size))
        batches = ((encode_batch(np.stack(imgs), caps)
                    for imgs, caps in wds.prefetch()))
    else:
        from dalle_tpu.data.text_image import TextImageDataset
        ds = TextImageDataset(args.image_text_folder,
                              image_size=args.image_size, shuffle=True,
                              seed=args.seed,
                              text_from_filename=args.text_from_filename)
        raw = ds.batches(args.batch_size, epochs=args.epochs)
        batches = (encode_batch(imgs, caps) for imgs, caps in raw)

    # metrics sink: JSONL always; wandb scalars/images/artifacts when asked
    # (reference legacy/train_dalle.py:463-476,639-649,667-669)
    from dalle_tpu.train.metrics import MetricsLogger
    metrics_writer = None
    if is_root:
        metrics_writer = MetricsLogger(
            path=os.path.join(args.output_dir, "metrics.jsonl"),
            use_wandb=args.wandb, project=args.wandb_project,
            run_name=args.wandb_name,
            config={"model": model_cfg.to_dict(), "train": train_cfg.to_dict()})

    # periodic in-training sampling (reference :639-649)
    sample_fn = None
    if args.sample_every_steps:
        import jax
        os.makedirs(args.sample_dir, exist_ok=True)
        sample_text = tokenizer.tokenize(["sample"], args.text_seq_len,
                                         truncate_text=True)

        def sample_fn(step):
            dv = DalleWithVae(trainer.model, trainer.state.params, vae)
            imgs = dv.generate_images(sample_text, jax.random.PRNGKey(step))
            save_image_grid(imgs, os.path.join(
                args.sample_dir, f"step{step}_{{}}.png"))
            if metrics_writer is not None:
                metrics_writer.log_images(step, imgs, key="generated",
                                          captions=["sample"] * len(imgs))
            if is_root:
                print(f"[step {step}] wrote sample to {args.sample_dir}")

    if is_root:
        print(f"DALLE: {trainer.num_params / 1e6:.1f}M params; "
              f"mesh {dict(trainer.mesh.shape)}; vae {type(vae).__name__}")
    log = print if is_root else (lambda *a, **k: None)

    steps = args.steps
    if args.flops_profiler:
        steps = 201  # profile at 200 then stop (reference :656-657)
    install_resilience(args, trainer, log=log)
    trainer.fit(batches, steps=steps, log=log, sample_fn=sample_fn,
                metrics_writer=metrics_writer)

    final = int(trainer.state.step)
    if trainer.ckpt.latest_step() != final:
        trainer.ckpt.save(final, trainer.state, trainer._meta())
    # drain the async writer before returning: a caller (or the next
    # process) must find the final step durable, not in flight
    trainer.ckpt.wait_until_finished()
    if metrics_writer is not None:
        metrics_writer.close()
    if is_root:
        print(f"done at step {final}; checkpoints in {args.output_dir}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
