#!/usr/bin/env python
"""Gateway smoke — the CI gate for dalle_tpu/gateway (docs/SERVING.md).

A loopback HTTP/SSE gateway over two tiny replicas, asserting the serving
contracts end-to-end over a real socket:

  * streaming — one SSE request streams every committed grid row in order
    (fmap rows × fmap tokens) and the concatenated rows equal the ``done``
    tokens equal single-request ``generate_images_tokens`` BITWISE;
  * graftscope tracing — the streamed request's spans across the gateway
    connection thread, router, replica worker and engine loop all share
    ONE trace_id (echoed as the X-Request-Id header and in every SSE
    event), and ``obs_report --request <id>`` reassembles them into a
    single ordered timeline: queue-wait → prefill → per-row decode → SSE
    flush;
  * concurrency/multi-tenancy — parallel streamed + blocking requests from
    two tenants all complete token-exact;
  * admission — a burst-1 tenant's second immediate request gets 429 with
    Retry-After (quota), /metrics exposes the reject counters with REAL
    {tenant,reason} labels, and the SLO burn-rate sentry flips to BURNING
    on the reject stream (the ``dalle_slo_*`` gauge family);
  * replica kill — a replica dies mid-stream after 2 rows; the failover
    completes the stream bitwise-exact under the SAME trace_id, and the
    flight recorder dumps a post-mortem bundle (a CI artifact, under
    ``<outdir>/flight/``) holding the replica_failed + failover lifecycle
    events and the dying worker's last decode-row spans;
  * /v1/images product loop (graftloom) — a multi-candidate request over
    the real socket: N candidates share ONE engine prefill
    (``DALLE.serve_refill_shared``), the post-decode pipeline batches them
    through dVAE pixels and the CLIP rerank stage, and every candidate's
    tokens come back BITWISE equal to independent single-request
    generation; SSE streams per-candidate rows with preview pixel bands
    then a final ``ranked`` event; bad n_candidates/top_k → 400 before
    admission; ``obs_report`` prints the IMAGES verdict line;
  * AOT cold start — a replica whose engine loaded the serialized
    executables serves its FIRST requests with ZERO backend compiles
    (asserted via the compile counter; phase A warms every eager op in the
    process through a jit replica first, so the zero is exactly "no
    retrace, no program compile on the cold replica" — a fresh jit engine
    in the same position pays its step/refill compiles). The widened
    graftloom bundle (4 programs incl. refill_shared) serves a cold
    /v1/images request inside the same zero-compile window. A second
    window (graftpage) pins the same zero for a CHUNK-ON engine — the
    fixed chunk-width program family made chunked prefill exportable —
    and serves a cond_scale request inside it (CFG is state data, not a
    new program), bitwise vs generate_images_tokens(cond_scale=...).

Artifacts (smoke.json, gateway_spans.jsonl, gateway_trace.json,
metrics.jsonl, flight/) land in ``--outdir`` — the dir ci.yml uploads
alongside serve_artifacts.
Run: JAX_PLATFORMS=cpu python scripts/gateway_smoke.py
"""

import argparse
import glob
import json
import os
import subprocess
import sys
import tempfile
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _post(address: str, payload: dict, timeout: float = 120.0,
          path: str = "/v1/generate"):
    import http.client
    host, port = address.split("//")[1].rsplit(":", 1)
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
    conn.request("POST", path, json.dumps(payload),
                 {"Content-Type": "application/json"})
    return conn, conn.getresponse()


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--outdir", type=str, default="gateway_artifacts")
    ap.add_argument("--slots", type=int, default=2)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    os.makedirs(args.outdir, exist_ok=True)

    import jax
    import numpy as np

    from dalle_tpu import obs
    from dalle_tpu.obs import lockorder, wiretap

    # graftsync runtime half: every dalle_tpu lock created from here on is
    # instrumented; the end of the smoke asserts the acquisition order this
    # real run exhibited is acyclic and within the static golden
    lockorder.install()
    # graftwire runtime half: record any frame touching the socket
    # transport (this smoke's replicas are in-process, so the set is
    # usually empty — the assertion is that nothing observed ESCAPES the
    # golden; fleet_smoke provides the non-empty cross-process run)
    wiretap.install()
    from dalle_tpu.config import DalleConfig
    from dalle_tpu.gateway import (AdmissionController, Gateway, Replica,
                                   ReplicaRouter, TenantQuotas, iter_sse,
                                   save_engine_aot)
    from dalle_tpu.models.dalle import DALLE, init_dalle

    cfg = DalleConfig(num_text_tokens=32, text_seq_len=6, dim=64, depth=2,
                      heads=2, dim_head=32, image_size=16,
                      image_vocab_size=24, image_fmap_size=4)
    model, params = init_dalle(cfg, jax.random.PRNGKey(args.seed), batch=2)
    rng = np.random.RandomState(args.seed)
    n_req = 6
    texts = [rng.randint(1, 20, (cfg.text_seq_len,)).astype(np.int32)
             for _ in range(n_req)]
    refs = {i: np.asarray(model.apply(
        params, np.asarray(t[None]), jax.random.PRNGKey(1000 + i),
        method=DALLE.generate_images_tokens)[0]).tolist()
        for i, t in enumerate(texts)}
    # /v1/images references: candidate i of a seed-s request samples under
    # seed s+i — texts[0] with base seed 1000 reuses refs[0]/refs[1], and a
    # second base (4000) pins the independence of the fan-out seeds
    img_refs = {s: np.asarray(model.apply(
        params, np.asarray(texts[0][None]), jax.random.PRNGKey(s),
        method=DALLE.generate_images_tokens)[0]).tolist()
        for s in (1000, 1001, 4000, 4001)}

    # the product loop's other two models: a tiny dVAE for pixel decode and
    # a tiny CLIP reranker, shared by every gateway phase through ONE
    # pipeline so phase B's zero-compile window inherits warm programs
    from dalle_tpu.config import ClipConfig, DVAEConfig
    from dalle_tpu.models.clip import init_clip
    from dalle_tpu.models.dvae import init_dvae
    from dalle_tpu.models.wrapper import DiscreteVAEAdapter
    from dalle_tpu.serve import ImagePipeline
    vcfg = DVAEConfig(image_size=16, num_tokens=24, codebook_dim=16,
                      num_layers=2, num_resnet_blocks=0, hidden_dim=8)
    vmodel, vparams = init_dvae(vcfg, jax.random.PRNGKey(args.seed + 1))
    vae = DiscreteVAEAdapter(vmodel, vparams)
    ccfg = ClipConfig(dim_text=32, dim_image=32, dim_latent=32,
                      num_text_tokens=32, text_enc_depth=1, text_seq_len=6,
                      text_heads=2, visual_enc_depth=1, visual_heads=2,
                      visual_image_size=16, visual_patch_size=8)
    clip_model, clip_params = init_clip(ccfg, jax.random.PRNGKey(args.seed))
    pipeline = ImagePipeline(vae=vae, clip=clip_model,
                             clip_params=clip_params)

    tracer = obs.configure()
    counter = obs.install_compile_counter()
    flight_dir = os.path.join(args.outdir, "flight")
    obs.configure_recorder(flight_dir, min_dump_interval_s=0.0,
                           sample_interval_s=0.2)
    # one burn-rate sentry across every gateway phase (the fleet's error
    # budget is one budget); min_events=5 so this short smoke can reach a
    # verdict — production keeps the default 10
    sentry = obs.BurnRateSentry(
        min_events=5,
        on_breach=lambda v: obs.dump_recorder(
            "slo_breach", extra={"dominating": v["dominating"]}))
    failures = []

    def check(ok, msg):
        print(("PASS " if ok else "FAIL ") + msg)
        if not ok:
            failures.append(msg)

    def make_engine():
        from dalle_tpu.serve import DecodeEngine
        return DecodeEngine(model, params, slots=args.slots)

    # AOT export first (the exporter pays these compiles, not the replicas)
    aot_dir = os.path.join(tempfile.mkdtemp(prefix="gateway_smoke_"), "aot")
    manifest = save_engine_aot(make_engine(), aot_dir)
    check(all(manifest["payload_bytes"][p] > 0
              for p in ("step", "refill", "refill_row", "refill_shared")),
          "AOT export serialized all four engine programs (incl. the "
          "graftloom shared-prefix refill)")

    # phase A: a jit replica serves the SSE + quota checks (and warms every
    # eager op in the process, so phase B's zero is the cold-start claim)
    jit_rep = Replica(make_engine(), replica_id="jit-0", maxsize=16).start()
    admission = AdmissionController(TenantQuotas(
        rate_per_s=200.0, burst=200.0, overrides={"capped": (0.02, 1)}))
    gw = Gateway(ReplicaRouter([jit_rep]), admission, vae=vae,
                 pipeline=pipeline, slo_sentry=sentry).start()

    conn, resp = _post(gw.address, {"text": texts[0].tolist(), "seed": 1000,
                                    "stream": True})
    check(resp.status == 200
          and resp.getheader("Content-Type") == "text/event-stream",
          "streamed request answers 200 text/event-stream")
    sse_tid = resp.getheader("X-Request-Id")
    check(bool(sse_tid), "X-Request-Id header echoes the minted trace_id")
    rows, done = [], None
    for event, data in iter_sse(resp):
        if event == "row":
            rows.append(data)
        elif event == "done":
            done = data
    conn.close()
    fmap = cfg.image_fmap_size
    check([d["row"] for d in rows] == list(range(fmap)),
          f"SSE framing: {fmap} grid rows streamed in order")
    check(all(len(d["tokens"]) == fmap for d in rows),
          "SSE framing: one fmap-width token row per event")
    streamed = [t for d in rows for t in d["tokens"]]
    check(done is not None and streamed == done["tokens"] == refs[0],
          "streamed rows ≡ done tokens ≡ single-request generation (bitwise)")
    check(all(d.get("trace_id") == sse_tid for d in rows)
          and done.get("trace_id") == sse_tid,
          "every SSE event carries the request's trace_id")

    # graftscope: the request's spans across gateway / replica / engine
    # threads all share the one trace_id minted at the HTTP door. The
    # engine/handler record their last spans a beat after the client sees
    # `done`, so poll briefly instead of racing them.
    import time as _time
    expect = {"gateway/request", "serve/request_queue_wait",
              "serve/prefill", "serve/decode_row", "serve/request",
              "gateway/sse_flush"}
    deadline = _time.time() + 5.0
    req_spans, names = [], set()
    while _time.time() < deadline:
        req_spans = [s for s in tracer.snapshot_spans()
                     if (s[5] or {}).get("trace_id") == sse_tid]
        names = {s[0] for s in req_spans}
        if expect <= names and len({s[3] for s in req_spans}) >= 2:
            break
        _time.sleep(0.05)
    check(expect <= names,
          f"one trace_id spans every layer (have {sorted(names)})")
    check(len({s[3] for s in req_spans}) >= 2,
          "request timeline crosses threads (connection + engine worker)")

    # concurrent multi-tenant traffic: blocking + streamed, two tenants
    results = {}

    def client(i):
        stream = i % 2 == 1
        conn, resp = _post(gw.address, {
            "text": texts[i].tolist(), "seed": 1000 + i, "stream": stream,
            "tenant": "teamA" if i % 2 else "teamB"})
        if stream:
            toks = None
            for event, data in iter_sse(resp):
                if event == "done":
                    toks = data["tokens"]
        else:
            toks = json.loads(resp.read())["tokens"]
        results[i] = toks
        conn.close()

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(1, n_req)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    check(all(results.get(i) == refs[i] for i in range(1, n_req)),
          f"{n_req - 1} concurrent multi-tenant requests all token-exact")

    # /v1/images: the graftloom product loop over the real socket ---------
    conn, resp = _post(gw.address, {"text": texts[0].tolist(), "seed": 1,
                                    "n_candidates": 3},
                       path="/v1/images")
    body = json.loads(resp.read())
    conn.close()
    check(resp.status == 400 and body["error"] == "bad_request",
          "images validation: n_candidates over the slot budget → 400 "
          "before admission")
    conn, resp = _post(gw.address, {"text": texts[0].tolist(), "seed": 1,
                                    "n_candidates": 2, "top_k": 3},
                       path="/v1/images")
    code = resp.status
    resp.read(), conn.close()
    check(code == 400, "images validation: top_k > n_candidates → 400")

    conn, resp = _post(gw.address, {"text": texts[0].tolist(), "seed": 1000,
                                    "n_candidates": 2, "top_k": 1},
                       path="/v1/images")
    ib = json.loads(resp.read())
    conn.close()
    check(resp.status == 200
          and ib["candidates"] == [img_refs[1000], img_refs[1001]],
          "/v1/images blocking: both candidates bitwise = independent "
          "single-request generation (seed, seed+1)")
    check(ib.get("reranked") is True and len(ib["scores"]) == 2
          and len(ib["top_k"]) == 1
          and ib["top_k"][0]["candidate"] == ib["order"][0]
          and "pixels_b64" in ib["top_k"][0],
          "/v1/images blocking: CLIP rerank applied, top-k entry carries "
          "decoded pixels")
    shared_n = jit_rep.engine.stats.shared_refills
    check(shared_n >= 1,
          f"engine paid shared prefills for the candidate group "
          f"(shared_refills={shared_n})")

    conn, resp = _post(gw.address, {"text": texts[0].tolist(), "seed": 4000,
                                    "n_candidates": 2, "top_k": 2,
                                    "stream": True, "pixels": True},
                       path="/v1/images")
    img_tid = resp.getheader("X-Request-Id")
    irows, ranked = [], None
    for event, data in iter_sse(resp):
        if event == "row":
            irows.append(data)
        elif event == "ranked":
            ranked = data
    conn.close()
    percand = {}
    for d in irows:
        percand.setdefault(d["candidate"], []).extend(d["tokens"])
    check(sorted(percand) == [0, 1]
          and percand[0] == img_refs[4000] and percand[1] == img_refs[4001],
          "/v1/images SSE: per-candidate rows concat to the exact "
          "per-seed generations")
    check(all("pixels_b64" in d for d in irows),
          "/v1/images SSE: every candidate row carries a preview pixel "
          "band")
    check(ranked is not None and ranked.get("reranked") is True
          and ranked["candidates"] == [img_refs[4000], img_refs[4001]]
          and len(ranked["top_k"]) == 2,
          "/v1/images SSE: final ranked event carries scores + all "
          "candidate grids")
    check(bool(img_tid) and ranked.get("trace_id") == img_tid,
          "/v1/images SSE: ranked event joins the request's trace_id")

    # quota: burst-1 tenant's second immediate request is rejected
    conn1, r1 = _post(gw.address, {"text": texts[0].tolist(), "seed": 2000,
                                   "tenant": "capped"})
    r1.read()
    conn2, r2 = _post(gw.address, {"text": texts[1].tolist(), "seed": 2001,
                                   "tenant": "capped"})
    body = json.loads(r2.read())
    check(r1.status == 200 and r2.status == 429
          and body["error"] == "quota"
          and r2.getheader("Retry-After") is not None,
          "quota exhaustion → 429 + Retry-After (first request served)")
    conn1.close(), conn2.close()

    import http.client
    host, port = gw.address.split("//")[1].rsplit(":", 1)
    mc = http.client.HTTPConnection(host, int(port), timeout=10)
    mc.request("GET", "/metrics")
    metrics_text = mc.getresponse().read().decode()
    mc.close()
    check("dalle_gateway_rejected_total" in metrics_text
          and "dalle_gateway_inflight" in metrics_text,
          "/metrics exposes gateway reject counter + inflight gauge")
    check('dalle_gateway_rejected_by_total{reason="quota",tenant="capped"}'
          in metrics_text,
          "/metrics renders real {tenant,reason} labels on the reject "
          "counter")
    check('dalle_slo_burn_rate{window="5m"}' in metrics_text,
          "/metrics exposes the dalle_slo_* burn-rate gauge family")

    # graftlens: /metrics is fleet-aggregated — the replica-side completion
    # counter sums to exactly the gateway's own completion count (here the
    # replicas share the process; fleet_smoke proves the cross-process sum)
    def metric_value(text, name):
        for line in text.splitlines():
            if line.startswith(name + " "):
                return float(line.split()[1])
        return None

    def fetch_metrics():
        c = http.client.HTTPConnection(host, int(port), timeout=10)
        c.request("GET", "/metrics")
        text = c.getresponse().read().decode()
        c.close()
        return text

    # the engine loop commits its completion counter a beat after the
    # client sees `done` — poll instead of racing it
    served = gw_done = None
    deadline = _time.time() + 5.0
    while _time.time() < deadline:
        served = metric_value(metrics_text,
                              "dalle_serve_requests_completed_total")
        gw_done = metric_value(metrics_text, "dalle_gateway_completed_total")
        if served is not None and served == gw_done:
            break
        _time.sleep(0.05)
        metrics_text = fetch_metrics()
    check(served is not None and gw_done is not None and served > 0
          and served == gw_done,
          f"/metrics: sum of per-replica completions ({served}) == gateway "
          f"completion count ({gw_done})")
    check("# TYPE dalle_serve_ttft_seconds histogram" in metrics_text
          and 'dalle_serve_ttft_seconds_bucket{le="' in metrics_text
          and metric_value(metrics_text, "dalle_serve_ttft_seconds_count")
          == served,
          "/metrics: native TTFT histogram (typed, cumulative buckets, "
          "count == completions)")
    check('# {trace_id="' in metrics_text,
          "/metrics: histogram buckets carry trace_id exemplars")
    check('dalle_usage_tokens_out_total{tenant="' in metrics_text,
          "/metrics: per-tenant usage counters rendered")
    gw.shutdown(drain=True, timeout=60)

    # mid-stream replica kill: the victim dies after 2 committed rows; the
    # router resubmits the SAME text/seed/trace_id to the standby, the
    # spliced stream stays bitwise-exact, and the flight recorder leaves a
    # post-mortem bundle behind
    victim = Replica(make_engine(), replica_id="victim", maxsize=16).start()
    standby = Replica(make_engine(), replica_id="standby",
                      maxsize=16).start()
    gwk = Gateway(ReplicaRouter([victim, standby]), AdmissionController(),
                  slo_sentry=sentry).start()
    victim.fail_after_rows(2)
    conn, resp = _post(gwk.address, {"text": texts[0].tolist(),
                                     "seed": 1000, "stream": True})
    kill_tid = resp.getheader("X-Request-Id")
    krows, kdone = [], None
    for event, data in iter_sse(resp):
        if event == "row":
            krows.append(data)
        elif event == "done":
            kdone = data
    conn.close()
    check(kdone is not None and kdone["tokens"] == refs[0]
          and kdone["failovers"] == 1 and kdone["replica"] == "standby"
          and [d["row"] for d in krows] == list(range(fmap)),
          "mid-stream replica kill: failover stream bitwise-exact, every "
          "row exactly once")
    kill_spans = [s for s in tracer.snapshot_spans()
                  if (s[5] or {}).get("trace_id") == kill_tid]
    qwait_n = sum(1 for s in kill_spans
                  if s[0] == "serve/request_queue_wait")
    check(qwait_n == 2 and all(d.get("trace_id") == kill_tid
                               for d in krows + [kdone]),
          "trace_id survives the failover resubmission (one identity, "
          "two admissions)")
    gwk.shutdown(drain=True, timeout=60)

    fo_bundles = sorted(glob.glob(
        os.path.join(flight_dir, "postmortem_failover_*")))
    check(bool(fo_bundles), "failover dumped a flight-recorder bundle")
    if fo_bundles:
        pm = json.load(open(os.path.join(fo_bundles[-1],
                                         "postmortem.json")))
        kinds = [e["kind"] for e in pm["events"]]
        check("replica_failed" in kinds and "failover" in kinds,
              "bundle event ring holds the replica death AND the failover")
        ktrace = json.load(open(os.path.join(fo_bundles[-1], "trace.json")))
        victim_rows = [e for e in ktrace["traceEvents"]
                       if e.get("args", {}).get("trace_id") == kill_tid
                       and e["name"] == "serve/decode_row"]
        check(bool(victim_rows),
              "bundle trace holds the dying worker's last decode-row spans")

    # phase B: AOT cold start — a NEVER-run replica loads the serialized
    # executables and serves with zero backend compiles. Built over a
    # FRESH model instance (same config/params, new object) so the
    # engine-level program sharing (serve/engine.py _shared_programs)
    # cannot hand it phase A's compiled programs: the zero below is the
    # AOT bundle's doing, nothing else's.
    model2, params2 = init_dalle(cfg, jax.random.PRNGKey(args.seed),
                                 batch=2)
    from dalle_tpu.serve import DecodeEngine
    aot_engine = DecodeEngine(model2, params2, slots=args.slots)
    aot_rep = Replica(aot_engine, replica_id="aot-0", maxsize=16,
                      aot_dir=aot_dir)
    check(aot_rep.aot_loaded and aot_engine.aot_loaded,
          "AOT bundle fingerprint-matched and loaded")
    gw2 = Gateway(ReplicaRouter([aot_rep.start()]),
                  AdmissionController(), vae=vae, pipeline=pipeline,
                  slo_sentry=sentry).start()
    before = counter.count
    cold = {}
    for i in range(2):
        conn, resp = _post(gw2.address, {"text": texts[i].tolist(),
                                         "seed": 1000 + i})
        cold[i] = json.loads(resp.read())["tokens"]
        conn.close()
    # the widened bundle's refill_shared executable serves a cold
    # multi-candidate request inside the same zero-compile window (the
    # shared pipeline's dVAE/CLIP programs were warmed in phase A)
    conn, resp = _post(gw2.address, {"text": texts[0].tolist(),
                                     "seed": 1000, "n_candidates": 2,
                                     "top_k": 1}, path="/v1/images")
    cold_img = json.loads(resp.read())
    conn.close()
    compiles = counter.count - before
    check(compiles == 0,
          f"AOT cold-start served first requests (incl. /v1/images) with "
          f"{compiles} backend compiles (retrace-free)")
    check(all(cold[i] == refs[i] for i in range(2)),
          "AOT-served tokens bit-exact vs jit reference")
    check(resp.status == 200
          and cold_img["candidates"] == [img_refs[1000], img_refs[1001]]
          and cold_img.get("reranked") is True,
          "AOT-served /v1/images candidates bit-exact + reranked")
    gw2.shutdown(drain=True, timeout=60)

    # phase B2 (graftpage): chunk-on engines AOT-export now too — the chunk
    # program set is the FIXED width family chunk_widths() enumerates, so
    # save_engine_aot no longer refuses prefill_chunk > 0 and a cold
    # chunk-on replica serves inside its own zero-compile window. The same
    # window serves a cond_scale request: classifier-free guidance is pure
    # state DATA (pair/cfg/uncond leaves), no new program.
    from dalle_tpu.gateway import load_engine_aot
    chunk_dir = os.path.join(os.path.dirname(aot_dir), "aot_chunk")
    chunk_exporter = DecodeEngine(model, params, slots=args.slots,
                                  prefill_chunk=3)
    cmanifest = save_engine_aot(chunk_exporter, chunk_dir)
    chunk_names = [p for p in cmanifest["programs"]
                   if p.startswith("refill_chunk_w")]
    check(sorted(int(p.split("_w")[1]) for p in chunk_names)
          == sorted(chunk_exporter.chunk_widths()),
          f"chunk-on AOT export carries one program per fixed width "
          f"{chunk_exporter.chunk_widths()}")
    # CFG reference BEFORE the zero-compile window opens (this sequential
    # generate pays its own compiles)
    cfg_ref = np.asarray(model.apply(
        params, np.asarray(texts[2][None]), jax.random.PRNGKey(7777),
        cond_scale=2.0, method=DALLE.generate_images_tokens)[0]).tolist()
    model3, params3 = init_dalle(cfg, jax.random.PRNGKey(args.seed),
                                 batch=2)
    chunk_engine = DecodeEngine(model3, params3, slots=args.slots,
                                prefill_chunk=3)
    check(load_engine_aot(chunk_engine, chunk_dir, strict=True),
          "chunk-on AOT bundle fingerprint-matched and loaded")
    chunk_rep = Replica(chunk_engine, replica_id="aot-chunk-0",
                        maxsize=16).start()
    gw3 = Gateway(ReplicaRouter([chunk_rep]), AdmissionController(),
                  vae=vae, pipeline=pipeline, slo_sentry=sentry).start()
    before = counter.count
    conn, resp = _post(gw3.address, {"text": texts[2].tolist(),
                                     "seed": 1002})
    chunk_tok = json.loads(resp.read())["tokens"]
    conn.close()
    conn, resp = _post(gw3.address, {"text": texts[2].tolist(),
                                     "seed": 7777, "cond_scale": 2.0})
    cfg_tok = json.loads(resp.read())["tokens"]
    conn.close()
    chunk_compiles = counter.count - before
    check(chunk_compiles == 0,
          f"chunk-on AOT cold start served (incl. a cond_scale pair) with "
          f"{chunk_compiles} backend compiles")
    check(chunk_tok == refs[2],
          "chunk-on AOT-served tokens bit-exact vs jit reference")
    check(cfg_tok == cfg_ref,
          "gateway cond_scale=2.0 tokens bit-exact vs "
          "generate_images_tokens(cond_scale=2.0)")
    gw3.shutdown(drain=True, timeout=60)

    spans = tracer.snapshot_spans()
    qwaits = [s for s in spans if s[0] == "serve/request_queue_wait"]
    check(len(qwaits) >= n_req,
          "per-request serve/request_queue_wait spans recorded")

    # SLO sentry: the reject stream burned through the error budget — the
    # gauges are live and the verdict is BURNING (dominated by a window)
    verdict = sentry.evaluate()
    snapshot = obs.metrics_snapshot()
    check(verdict["burning"] and snapshot.get("slo.burning") == 1.0
          and 'slo.burn_rate{window="5m"}' in snapshot,
          f"burn-rate sentry BURNING (dominating {verdict['dominating']}; "
          f"{sentry.bad_total}/{sentry.bad_total + sentry.good_total} bad)")
    slo_bundles = glob.glob(os.path.join(flight_dir,
                                         "postmortem_slo_breach_*"))
    check(bool(slo_bundles), "SLO breach dumped a flight-recorder bundle")

    n_spans = obs.export_spans_jsonl(
        os.path.join(args.outdir, "gateway_spans.jsonl"))
    obs.export_chrome_trace(os.path.join(args.outdir, "gateway_trace.json"),
                            request_tracks=True)
    with open(os.path.join(args.outdir, "metrics.jsonl"), "w") as fh:
        fh.write(json.dumps({"step": 0, **snapshot}) + "\n")

    # obs_report --request: the CLI reassembles the streamed request's
    # cross-thread spans into one ordered timeline
    rep = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__),
                                      "obs_report.py"),
         os.path.join(args.outdir, "gateway_spans.jsonl"),
         "--request", sse_tid],
        capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    tl = rep.stdout
    order = [tl.find(n) for n in ("serve/request_queue_wait",
                                  "serve/prefill", "serve/decode_row",
                                  "gateway/sse_flush")]
    check(rep.returncode == 0 and all(i >= 0 for i in order)
          and order == sorted(order),
          "obs_report --request: one ordered timeline "
          "(queue-wait → prefill → decode rows → SSE flush)")

    # obs_report summary prints the burn-rate verdict line off the gauges
    rep2 = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__),
                                      "obs_report.py"), args.outdir],
        capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    check("slo burn rate" in rep2.stdout and "BURNING" in rep2.stdout,
          "obs_report prints the slo burn-rate verdict (BURNING)")
    check("images product loop" in rep2.stdout
          and "IMAGES: RERANKING" in rep2.stdout,
          "obs_report prints the graftloom IMAGES verdict (RERANKING)")
    check("latency histograms" in rep2.stdout
          and "serve.ttft_seconds" in rep2.stdout
          and "p95=" in rep2.stdout,
          "obs_report renders TTFT p50/p95 from the native buckets")
    check("USAGE: metered" in rep2.stdout,
          "obs_report prints the per-tenant USAGE section")

    # graftsync cross-check: the lock-acquisition order this real
    # multi-threaded run exhibited must be acyclic and a subgraph of the
    # static golden (contracts/sync.json) — an observed edge the static
    # pass missed means the model has a blind spot worth closing
    from dalle_tpu.analysis.sync_flow import build_repo_model
    obs_edges = lockorder.observed_edges()
    obs_cycles = lockorder.cycles()
    check(not obs_cycles,
          f"observed lock-acquisition graph acyclic "
          f"({len(obs_edges)} edges over "
          f"{len(lockorder.observed_sites())} locks)")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    site_to_id = build_repo_model(root).lock_by_site()
    with open(os.path.join(root, "contracts", "sync.json")) as fh:
        golden_edges = {(d["src"], d["dst"])
                        for d in json.load(fh)["edges"]}
    unknown = [lockorder.format_edge(e) for e in obs_edges
               if e.src not in site_to_id or e.dst not in site_to_id]
    mapped = {(site_to_id[e.src], site_to_id[e.dst]) for e in obs_edges
              if e.src in site_to_id and e.dst in site_to_id}
    extra = sorted(f"{s} -> {d}" for s, d in mapped - golden_edges)
    check(not unknown and not extra,
          "observed lock graph ⊆ static golden (unknown locks: "
          f"{unknown or 'none'}; edges beyond golden: {extra or 'none'})")

    # graftwire cross-check: anything that DID touch the socket transport
    # must fit the golden protocol contract, and the lifecycle machines
    # the golden pins must be acyclic
    from dalle_tpu.analysis.wire_flow import lifecycle_cycles
    with open(os.path.join(root, "contracts", "wire.json")) as fh:
        wire_golden = json.load(fh)
    wire_frames = wiretap.observed()
    wire_violations = [str(v) for v in wiretap.conformance(wire_golden)]
    check(not wire_violations,
          f"observed wire frames ⊆ static golden ({len(wire_frames)} "
          f"distinct frame shapes; violations: {wire_violations or 'none'})")
    wire_cycles = lifecycle_cycles(
        {n: {"edges": [tuple(e) for e in m["edges"]]}
         for n, m in wire_golden["lifecycles"].items()})
    check(not wire_cycles,
          f"golden lifecycle machines acyclic ({wire_cycles or 'no cycles'})")

    summary = {
        "requests": n_req, "slots": args.slots,
        "lock_sites_observed": len(lockorder.observed_sites()),
        "lock_edges_observed": [lockorder.format_edge(e)
                                for e in obs_edges],
        "wire_frames_observed": [
            [verb, direction, kind, sorted(fields)]
            for verb, direction, kind, fields in wire_frames],
        "images_requests": snapshot.get("gateway.images_requests_total", 0),
        "images_candidates": snapshot.get(
            "gateway.images_candidates_total", 0),
        "images_reranked": snapshot.get("gateway.images_reranked_total", 0),
        "shared_refills": shared_n,
        "aot_payload_bytes": manifest["payload_bytes"],
        "aot_cold_start_compiles": compiles,
        "rejected_total": snapshot.get("gateway.rejected_total", 0),
        "slo_burning": bool(verdict["burning"]),
        "slo_dominating_window": verdict["dominating"],
        "failover_trace_id": kill_tid,
        "flight_bundles": sorted(os.path.basename(p) for p in glob.glob(
            os.path.join(flight_dir, "postmortem_*"))),
        "spans_exported": n_spans, "failures": failures,
    }
    with open(os.path.join(args.outdir, "smoke.json"), "w") as fh:
        json.dump(summary, fh, indent=2)
    obs.disable()
    obs.disable_recorder()
    print(json.dumps({"metric": "gateway_smoke", **summary}), flush=True)
    if failures:
        print(f"gateway_smoke: FAILED ({len(failures)} checks)")
        return 1
    print("gateway_smoke: GREEN")
    return 0


if __name__ == "__main__":
    sys.exit(main())
