#!/usr/bin/env python
"""Gateway smoke — the CI gate for dalle_tpu/gateway (docs/SERVING.md).

A loopback HTTP/SSE gateway over two tiny replicas, asserting the serving
contracts end-to-end over a real socket:

  * streaming — one SSE request streams every committed grid row in order
    (fmap rows × fmap tokens) and the concatenated rows equal the ``done``
    tokens equal single-request ``generate_images_tokens`` BITWISE;
  * concurrency/multi-tenancy — parallel streamed + blocking requests from
    two tenants all complete token-exact;
  * admission — a burst-1 tenant's second immediate request gets 429 with
    Retry-After (quota), and /metrics exposes the reject counters;
  * AOT cold start — a replica whose engine loaded the serialized
    executables serves its FIRST requests with ZERO backend compiles
    (asserted via the compile counter; phase A warms every eager op in the
    process through a jit replica first, so the zero is exactly "no
    retrace, no program compile on the cold replica" — a fresh jit engine
    in the same position pays its step/refill compiles).

Artifacts (smoke.json, gateway_spans.jsonl, metrics.jsonl) land in
``--outdir`` — the dir ci.yml uploads alongside serve_artifacts.
Run: JAX_PLATFORMS=cpu python scripts/gateway_smoke.py
"""

import argparse
import json
import os
import sys
import tempfile
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _post(address: str, payload: dict, timeout: float = 120.0):
    import http.client
    host, port = address.split("//")[1].rsplit(":", 1)
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
    conn.request("POST", "/v1/generate", json.dumps(payload),
                 {"Content-Type": "application/json"})
    return conn, conn.getresponse()


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--outdir", type=str, default="gateway_artifacts")
    ap.add_argument("--slots", type=int, default=2)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    os.makedirs(args.outdir, exist_ok=True)

    import jax
    import numpy as np

    from dalle_tpu import obs
    from dalle_tpu.config import DalleConfig
    from dalle_tpu.gateway import (AdmissionController, Gateway, Replica,
                                   ReplicaRouter, TenantQuotas, iter_sse,
                                   save_engine_aot)
    from dalle_tpu.models.dalle import DALLE, init_dalle

    cfg = DalleConfig(num_text_tokens=32, text_seq_len=6, dim=64, depth=2,
                      heads=2, dim_head=32, image_size=16,
                      image_vocab_size=24, image_fmap_size=4)
    model, params = init_dalle(cfg, jax.random.PRNGKey(args.seed), batch=2)
    rng = np.random.RandomState(args.seed)
    n_req = 6
    texts = [rng.randint(1, 20, (cfg.text_seq_len,)).astype(np.int32)
             for _ in range(n_req)]
    refs = {i: np.asarray(model.apply(
        params, np.asarray(t[None]), jax.random.PRNGKey(1000 + i),
        method=DALLE.generate_images_tokens)[0]).tolist()
        for i, t in enumerate(texts)}

    tracer = obs.configure()
    counter = obs.install_compile_counter()
    failures = []

    def check(ok, msg):
        print(("PASS " if ok else "FAIL ") + msg)
        if not ok:
            failures.append(msg)

    def make_engine():
        from dalle_tpu.serve import DecodeEngine
        return DecodeEngine(model, params, slots=args.slots)

    # AOT export first (the exporter pays these compiles, not the replicas)
    aot_dir = os.path.join(tempfile.mkdtemp(prefix="gateway_smoke_"), "aot")
    manifest = save_engine_aot(make_engine(), aot_dir)
    check(all(manifest["payload_bytes"][p] > 0
              for p in ("step", "refill", "refill_row")),
          "AOT export serialized all three engine programs")

    # phase A: a jit replica serves the SSE + quota checks (and warms every
    # eager op in the process, so phase B's zero is the cold-start claim)
    jit_rep = Replica(make_engine(), replica_id="jit-0", maxsize=16).start()
    admission = AdmissionController(TenantQuotas(
        rate_per_s=200.0, burst=200.0, overrides={"capped": (0.02, 1)}))
    gw = Gateway(ReplicaRouter([jit_rep]), admission).start()

    conn, resp = _post(gw.address, {"text": texts[0].tolist(), "seed": 1000,
                                    "stream": True})
    check(resp.status == 200
          and resp.getheader("Content-Type") == "text/event-stream",
          "streamed request answers 200 text/event-stream")
    rows, done = [], None
    for event, data in iter_sse(resp):
        if event == "row":
            rows.append(data)
        elif event == "done":
            done = data
    conn.close()
    fmap = cfg.image_fmap_size
    check([d["row"] for d in rows] == list(range(fmap)),
          f"SSE framing: {fmap} grid rows streamed in order")
    check(all(len(d["tokens"]) == fmap for d in rows),
          "SSE framing: one fmap-width token row per event")
    streamed = [t for d in rows for t in d["tokens"]]
    check(done is not None and streamed == done["tokens"] == refs[0],
          "streamed rows ≡ done tokens ≡ single-request generation (bitwise)")

    # concurrent multi-tenant traffic: blocking + streamed, two tenants
    results = {}

    def client(i):
        stream = i % 2 == 1
        conn, resp = _post(gw.address, {
            "text": texts[i].tolist(), "seed": 1000 + i, "stream": stream,
            "tenant": "teamA" if i % 2 else "teamB"})
        if stream:
            toks = None
            for event, data in iter_sse(resp):
                if event == "done":
                    toks = data["tokens"]
        else:
            toks = json.loads(resp.read())["tokens"]
        results[i] = toks
        conn.close()

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(1, n_req)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    check(all(results.get(i) == refs[i] for i in range(1, n_req)),
          f"{n_req - 1} concurrent multi-tenant requests all token-exact")

    # quota: burst-1 tenant's second immediate request is rejected
    conn1, r1 = _post(gw.address, {"text": texts[0].tolist(), "seed": 2000,
                                   "tenant": "capped"})
    r1.read()
    conn2, r2 = _post(gw.address, {"text": texts[1].tolist(), "seed": 2001,
                                   "tenant": "capped"})
    body = json.loads(r2.read())
    check(r1.status == 200 and r2.status == 429
          and body["error"] == "quota"
          and r2.getheader("Retry-After") is not None,
          "quota exhaustion → 429 + Retry-After (first request served)")
    conn1.close(), conn2.close()

    import http.client
    host, port = gw.address.split("//")[1].rsplit(":", 1)
    mc = http.client.HTTPConnection(host, int(port), timeout=10)
    mc.request("GET", "/metrics")
    metrics_text = mc.getresponse().read().decode()
    mc.close()
    check("dalle_gateway_rejected_total" in metrics_text
          and "dalle_gateway_inflight" in metrics_text,
          "/metrics exposes gateway reject counter + inflight gauge")
    gw.shutdown(drain=True, timeout=60)

    # phase B: AOT cold start — a NEVER-run replica loads the serialized
    # executables and serves with zero backend compiles. Built over a
    # FRESH model instance (same config/params, new object) so the
    # engine-level program sharing (serve/engine.py _shared_programs)
    # cannot hand it phase A's compiled programs: the zero below is the
    # AOT bundle's doing, nothing else's.
    model2, params2 = init_dalle(cfg, jax.random.PRNGKey(args.seed),
                                 batch=2)
    from dalle_tpu.serve import DecodeEngine
    aot_engine = DecodeEngine(model2, params2, slots=args.slots)
    aot_rep = Replica(aot_engine, replica_id="aot-0", maxsize=16,
                      aot_dir=aot_dir)
    check(aot_rep.aot_loaded and aot_engine.aot_loaded,
          "AOT bundle fingerprint-matched and loaded")
    gw2 = Gateway(ReplicaRouter([aot_rep.start()]),
                  AdmissionController()).start()
    before = counter.count
    cold = {}
    for i in range(2):
        conn, resp = _post(gw2.address, {"text": texts[i].tolist(),
                                         "seed": 1000 + i})
        cold[i] = json.loads(resp.read())["tokens"]
        conn.close()
    compiles = counter.count - before
    check(compiles == 0,
          f"AOT cold-start served first requests with {compiles} backend "
          "compiles (retrace-free)")
    check(all(cold[i] == refs[i] for i in range(2)),
          "AOT-served tokens bit-exact vs jit reference")
    gw2.shutdown(drain=True, timeout=60)

    spans = tracer.snapshot_spans()
    qwaits = [s for s in spans if s[0] == "serve/request_queue_wait"]
    check(len(qwaits) >= n_req,
          "per-request serve/request_queue_wait spans recorded")

    n_spans = obs.export_spans_jsonl(
        os.path.join(args.outdir, "gateway_spans.jsonl"))
    snapshot = obs.metrics_snapshot()
    with open(os.path.join(args.outdir, "metrics.jsonl"), "w") as fh:
        fh.write(json.dumps({"step": 0, **snapshot}) + "\n")
    summary = {
        "requests": n_req, "slots": args.slots,
        "aot_payload_bytes": manifest["payload_bytes"],
        "aot_cold_start_compiles": compiles,
        "rejected_total": snapshot.get("gateway.rejected_total", 0),
        "spans_exported": n_spans, "failures": failures,
    }
    with open(os.path.join(args.outdir, "smoke.json"), "w") as fh:
        json.dump(summary, fh, indent=2)
    obs.disable()
    print(json.dumps({"metric": "gateway_smoke", **summary}), flush=True)
    if failures:
        print(f"gateway_smoke: FAILED ({len(failures)} checks)")
        return 1
    print("gateway_smoke: GREEN")
    return 0


if __name__ == "__main__":
    sys.exit(main())
