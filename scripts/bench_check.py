#!/usr/bin/env python
"""Perf-regression sentry: diff the newest bench round against the prior one.

The repo accumulates one ``BENCH_r*.json`` / ``MULTICHIP_r*.json`` pair per
round (bench.py output + the multichip dryrun capture). Perf history only
helps if someone actually reads it — this script is that someone: it parses
the metric records out of the two newest rounds of each family, compares
every metric shared between them against a tolerance band, and prints a
verdict per metric plus one overall line:

    bench_check: OK         — every shared metric within the band
    bench_check: REGRESSED  — at least one metric moved the BAD way by
                              more than the tolerance
    (IMPROVED / NEW / MISSING are annotated per metric, never fatal)

Direction is inferred from the metric name: ``*time*``/``*latency*``/
``*ratio*``/``*_ms``/``*_s`` are lower-is-better, everything else (tok/s,
req/s, MFU) higher-is-better.

Wired as an ADVISORY ci_local stage: it always exits 0 unless ``--strict``
— this sandbox's CPU-mesh numbers jitter with box load, so a regression
here is a prompt to look, not a build failure. On real hardware, run with
``--strict --tolerance 0.05``.
"""

import argparse
import glob
import json
import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_LOWER_BETTER = re.compile(r"time|latency|ratio|_ms\b|_s\b")


def _round_of(path: str) -> int:
    m = re.search(r"_r(\d+)\.json$", path)
    return int(m.group(1)) if m else -1


def extract_metrics(path: str) -> dict:
    """{metric_name: value} from a round capture: the ``parsed`` record
    when present, plus every JSON metric line in the captured ``tail``."""
    try:
        doc = json.load(open(path))
    except (OSError, json.JSONDecodeError) as exc:
        print(f"bench_check: unreadable {path}: {exc!r}", file=sys.stderr)
        return {}
    out = {}
    recs = []
    if isinstance(doc.get("parsed"), dict):
        recs.append(doc["parsed"])
    for line in (doc.get("tail") or "").splitlines():
        line = line.strip()
        if line.startswith("{") and '"metric"' in line:
            try:
                recs.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    for rec in recs:
        name, value = rec.get("metric"), rec.get("value")
        if isinstance(name, str) and isinstance(value, (int, float)):
            out[name] = float(value)
    return out


def newest_pair(family_glob: str, root: str = ROOT):
    """(newest_path, prior_path) by round number; (path, None) when only
    one round exists, (None, None) when none do."""
    paths = sorted(glob.glob(os.path.join(root, family_glob)),
                   key=_round_of)
    if not paths:
        return None, None
    if len(paths) == 1:
        return paths[0], None
    return paths[-1], paths[-2]


def compare(new: dict, old: dict, tolerance: float):
    """Per-metric verdict rows: (name, old, new, rel_delta, verdict)."""
    rows = []
    for name in sorted(set(new) | set(old)):
        nv, ov = new.get(name), old.get(name)
        if ov is None:
            rows.append((name, None, nv, None, "NEW"))
            continue
        if nv is None:
            rows.append((name, ov, None, None, "MISSING"))
            continue
        if ov == 0:
            rows.append((name, ov, nv, None, "OK" if nv == 0 else "NEW"))
            continue
        delta = (nv - ov) / abs(ov)
        lower_better = bool(_LOWER_BETTER.search(name))
        bad = delta > tolerance if lower_better else delta < -tolerance
        good = delta < -tolerance if lower_better else delta > tolerance
        rows.append((name, ov, nv, delta,
                     "REGRESSED" if bad else
                     "IMPROVED" if good else "OK"))
    return rows


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--tolerance", type=float, default=0.10,
                    help="relative band before a move counts (default 10%%)")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 on REGRESSED (default: advisory, exit 0)")
    ap.add_argument("--root", type=str, default=ROOT,
                    help="directory holding the BENCH_r*/MULTICHIP_r* "
                         "rounds (default: the repo root)")
    args = ap.parse_args(argv)

    regressed = 0
    compared = 0
    for family in ("BENCH_r*.json", "MULTICHIP_r*.json"):
        newest, prior = newest_pair(family, args.root)
        label = family.split("_")[0]
        if newest is None:
            print(f"-- {label}: no rounds found")
            continue
        if prior is None:
            print(f"-- {label}: only one round "
                  f"({os.path.basename(newest)}) — nothing to diff")
            continue
        new_m = extract_metrics(newest)
        old_m = extract_metrics(prior)
        print(f"== {label}: {os.path.basename(prior)} → "
              f"{os.path.basename(newest)} (tolerance "
              f"±{args.tolerance:.0%})")
        if not new_m and not old_m:
            print("   (no metric records in either round)")
            continue
        if not new_m:
            # an empty newest trajectory (fresh clone / a placeholder round
            # committed before its capture ran) is a NEW baseline, not a
            # wall of MISSING verdicts — the advisory stage stays quiet on
            # first run and the next captured round diffs normally
            print(f"   NEW        (no metric records in "
                  f"{os.path.basename(newest)} — treating the trajectory "
                  "as a fresh baseline, nothing to diff)")
            continue
        for name, ov, nv, delta, verdict in compare(new_m, old_m,
                                                    args.tolerance):
            compared += verdict in ("OK", "IMPROVED", "REGRESSED")
            regressed += verdict == "REGRESSED"
            dtxt = f"{delta:+.2%}" if delta is not None else "  —  "
            ovt = f"{ov:.6g}" if ov is not None else "—"
            nvt = f"{nv:.6g}" if nv is not None else "—"
            print(f"   {verdict:<10}{name}: {ovt} → {nvt} ({dtxt})")
    verdict = "REGRESSED" if regressed else "OK"
    print(f"bench_check: {verdict} ({compared} metrics compared, "
          f"{regressed} regressed)")
    return 1 if (regressed and args.strict) else 0


if __name__ == "__main__":
    sys.exit(main())
