#!/usr/bin/env python
"""Observability + host-overlap smoke: a short synthetic traced DALLE fit
with every PR3 overlap layer ON (device prefetch, async checkpointing,
deferred metrics) AND the graftpulse health taps fused into the step, then
assert the telemetry AND overlap contracts end to end (the CI stage behind
docs/OBSERVABILITY.md and docs/PERFORMANCE.md):

  1. the Chrome trace JSON is well-formed, contains fit/batch_wait,
     fit/dispatch and fit/sync spans, and the in-band sync span NESTS inside
     its step's dispatch window (trainer._finish_step runs inside
     fit/dispatch; on-demand/flush syncs are exempt);
  2. the metrics JSONL carries the per-step breakdown — t_batch_wait_s /
     t_dispatch_s / t_sync_s / t_h2d_s, a data-starvation ratio, the HBM
     gauge, and t_ckpt_s on the records after each save boundary;
  3. OVERLAP: steady-state t_batch_wait_s + t_sync_s is ~0 WITH the health
     taps on (the graftpulse free-tap contract: the per-layer-group
     vitals ride the existing deferred-metrics fetch, zero added host
     syncs), and a step crossing a checkpoint boundary stays within a
     bounded multiple of the median step time;
  4. the watchdog (armed with a generous deadline) stayed quiet;
  5. measured span overhead extrapolated to a full step's span count is
     < 1% of the median step time;
  6. GRAFTPULSE: health/* columns present in the records; the pinned
     graftir goldens for all four trainer steps carry ZERO host-transfer
     primitives (the taps are in-graph reductions only — any drift there
     fails the graftir stage first, this re-asserts the transfer half);
  7. ANOMALY PATH, end to end: a second tiny dVAE fit with a synthetic
     codebook collapse injected (the perplexity floor forced above any
     reachable usage perplexity) must fire the codebook-collapse detector
     EXACTLY once — one flight-recorder bundle in health_artifacts/, and
     an obs_report MODEL-HEALTH: DEGRADED verdict naming the detector and
     layer group.

Artifacts (trace.json, spans.jsonl, metrics.jsonl, breakdown.json,
health_artifacts/ with the collapse bundle + vae_metrics.jsonl, the
obs_report summary) land in --outdir; ci.yml uploads them so every CI run
leaves an openable Perfetto trace + the step-breakdown behind.

Run: JAX_PLATFORMS=cpu python scripts/obs_smoke.py --outdir obs_artifacts
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

FAILURES = []


def check(ok: bool, what: str):
    print(("ok   " if ok else "FAIL ") + what)
    if not ok:
        FAILURES.append(what)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--outdir", default="./obs_smoke_out")
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--save_every", type=int, default=5)
    args = ap.parse_args(argv)
    os.makedirs(args.outdir, exist_ok=True)

    # the graftpulse live-contract probe (check 6) compiles a trainer step
    # on a 2x2 dp/fsdp mesh, so force the 8-device CPU platform BEFORE jax
    # initializes (the conftest trick; the main fit still pins devices[:1])
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()

    import jax
    import numpy as np
    from dalle_tpu import obs
    from dalle_tpu.config import (DalleConfig, MeshConfig, ObsConfig,
                                  TrainConfig)
    from dalle_tpu.obs.report import span_overhead_s, summarize_run
    from dalle_tpu.parallel.mesh import build_mesh
    from dalle_tpu.train.metrics import MetricsLogger
    from dalle_tpu.train.trainer_dalle import DalleTrainer

    tiny = DalleConfig(num_text_tokens=32, text_seq_len=8, dim=32, depth=2,
                       heads=2, dim_head=16, image_size=16,
                       image_vocab_size=32, image_fmap_size=4)
    mesh_cfg = MeshConfig()
    tc = TrainConfig(
        batch_size=4, log_every=1, metrics_every=1,
        save_every_steps=args.save_every, keep_n_checkpoints=2,
        preflight_checkpoint=False,
        async_checkpointing=True, device_prefetch=2, defer_metrics=True,
        rollback_snapshot="auto",
        checkpoint_dir=os.path.join(args.outdir, "ckpt"),
        mesh=mesh_cfg,
        obs=ObsConfig(trace=True, trace_dir=args.outdir,
                      watchdog_deadline_s=300.0, device_poll_every=1,
                      health=True))
    # one explicit device: an inherited XLA_FLAGS=...device_count=8 would
    # otherwise auto-scale dp to 8 and reject the batch-4 sharding
    trainer = DalleTrainer(tiny, tc, mesh=build_mesh(
        mesh_cfg, devices=jax.devices()[:1]))

    rng = np.random.RandomState(0)
    batches = [(rng.randint(1, tiny.num_text_tokens, (4, tiny.text_seq_len)),
                rng.randint(0, tiny.image_vocab_size, (4, tiny.image_seq_len)))
               for _ in range(args.steps)]
    metrics_path = os.path.join(args.outdir, "metrics.jsonl")
    if os.path.exists(metrics_path):
        os.remove(metrics_path)
    writer = MetricsLogger(path=metrics_path)
    trainer.fit(iter(batches), steps=args.steps, metrics_writer=writer)
    writer.close()

    # -- 1. trace validity + nesting ---------------------------------------
    trace_path = os.path.join(args.outdir, "trace.json")
    with open(trace_path) as fh:
        doc = json.load(fh)
    events = doc.get("traceEvents", [])
    names = {e["name"] for e in events}
    check(len(events) > 0, f"trace.json parses; {len(events)} events")
    for want in ("fit/step", "fit/batch_wait", "fit/dispatch", "fit/sync",
                 "dalle/step", "dalle/shard_batch", "fit/checkpoint",
                 "ckpt/snapshot", "ckpt/snapshot_good", "data/h2d"):
        check(want in names, f"span present: {want}")
    # nesting: every IN-BAND fit/sync must lie inside some fit/dispatch
    # interval (on-demand save-boundary fetches and the defer-flush run in
    # the fit loop itself, outside dispatch — by design)
    dispatch = [(e["ts"], e["ts"] + e["dur"]) for e in events
                if e["name"] == "fit/dispatch"]
    syncs = [(e["ts"], e["ts"] + e["dur"]) for e in events
             if e["name"] == "fit/sync"
             and not (e.get("args") or {}).get("on_demand")
             and not (e.get("args") or {}).get("flush")]
    nested = all(any(lo <= s0 and s1 <= hi + 1 for lo, hi in dispatch)
                 for s0, s1 in syncs)
    check(bool(syncs) and nested, "in-band fit/sync spans nest inside fit/dispatch")

    # -- 2. breakdown metrics in the JSONL ---------------------------------
    with open(metrics_path) as fh:
        recs = [json.loads(ln) for ln in fh if ln.strip()]
    check(len(recs) >= args.steps - 1,
          f"metrics.jsonl has {len(recs)} records (≥ steps-1)")
    full = [r for r in recs if "data_starvation" in r]
    check(bool(full), "records with the windowed breakdown exist")
    last = full[-1] if full else {}
    for col in ("t_batch_wait_s", "t_dispatch_s", "t_sync_s", "t_h2d_s",
                "data_starvation", "hbm_bytes_in_use", "compiles_total"):
        check(any(col in r for r in recs), f"metric column present: {col}")
    check(0.0 <= last.get("data_starvation", -1) <= 1.0,
          f"data_starvation in [0,1] (last={last.get('data_starvation')})")
    n_ckpt = sum(1 for r in recs if r.get("t_ckpt_s"))
    check(n_ckpt >= 1, f"t_ckpt_s recorded after save boundaries ({n_ckpt})")

    # -- 3. overlap: steady-state stalls ~0; ckpt-boundary step bounded ----
    # per-step walls from fit/step spans, keyed by their step arg; the first
    # two steps carry XLA compiles and are excluded from the steady state
    step_spans = {int(e["args"]["step"]): e["dur"] / 1e6 for e in events
                  if e["name"] == "fit/step" and (e.get("args") or {}).get("step") is not None}
    ckpt_steps = {int(e["args"]["step"]) - 1 for e in events
                  if e["name"] == "fit/checkpoint"}   # span step arg is post-increment
    steady = sorted(dur for s, dur in step_spans.items()
                    if s >= 2 and s not in ckpt_steps)
    boundary = [dur for s, dur in step_spans.items()
                if s >= 2 and s in ckpt_steps]
    med_step = steady[len(steady) // 2] if steady else float("nan")
    waits = sorted(r["t_batch_wait_s"] + r["t_sync_s"] for r in recs
                   if "t_batch_wait_s" in r and not r.get("t_ckpt_s"))
    if waits:
        med_wait = waits[len(waits) // 2]
        # "≈ 0": an in-memory iterator + device-resident batches + deferred
        # sync leave only bookkeeping — bounded by 10% of a (tiny, ~ms-scale)
        # step with a 5 ms absolute floor for CI scheduler noise
        bound = max(0.10 * med_step, 0.005)
        check(med_wait < bound,
              f"steady-state batch_wait+sync ≈ 0 (median {med_wait * 1e3:.3f}ms"
              f" < {bound * 1e3:.2f}ms)")
    else:
        check(False, "no steady-state wait/sync records")
    if boundary and steady:
        worst = max(boundary)
        # async save pays one snapshot, not snapshot+serialize+write: the
        # boundary step must stay within ~2× the median step. The 1 s
        # absolute floor covers the toy regime this smoke runs in: orbax's
        # fixed host dispatch cost (~0.2-0.7 s, amplified on a 1-core CI box
        # where the background writer shares the core) dwarfs a ~20 ms toy
        # step but vanishes next to a real model's step — there the 2× term
        # is the binding constraint
        bound = max(2.0 * med_step, med_step + 1.0)
        check(worst <= bound,
              f"checkpoint-boundary step bounded ({worst * 1e3:.1f}ms ≤ "
              f"{bound * 1e3:.1f}ms; median step {med_step * 1e3:.1f}ms)")
    else:
        check(False, "no checkpoint-boundary step spans found")

    # -- 4. watchdog quiet -------------------------------------------------
    wd = trainer.last_watchdog
    check(wd is not None and wd.stall_count == 0,
          f"watchdog quiet (stalls={getattr(wd, 'stall_count', '?')})")

    # -- 5. span overhead < 1% of step time --------------------------------
    per_span = span_overhead_s()
    spans_per_step = len(events) / max(args.steps, 1)
    dispatch_times = sorted(r["t_dispatch_s"] for r in recs
                            if "t_dispatch_s" in r)
    if dispatch_times:
        med_disp = dispatch_times[len(dispatch_times) // 2]
        overhead = per_span * spans_per_step
        check(overhead < 0.01 * med_disp,
              f"span overhead {overhead * 1e6:.1f}µs ({spans_per_step:.0f} "
              f"spans/step × {per_span * 1e9:.0f}ns) < 1% of median step "
              f"{med_disp * 1e3:.2f}ms")
    else:
        check(False, "no t_dispatch_s records — overhead gate unmeasurable")

    # -- 6. graftpulse: live taps + pinned-golden transfer invariant -------
    health_cols = sorted({k for r in recs for k in r
                          if k.startswith("health/")})
    check(any(k.startswith("health/grad_norm/") for k in health_cols)
          and any(k.startswith("health/update_ratio/") for k in health_cols)
          and any(k.startswith("health/nonfinite_frac/") for k in health_cols),
          f"health taps in records ({len(health_cols)} health columns)")
    nf = [r[k] for r in recs for k in r
          if k.startswith("health/nonfinite_frac/")]
    check(bool(nf) and all(v == 0.0 for v in nf),
          "nonfinite_frac taps all zero on a healthy run")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for entry in ("train_step_dalle", "train_step_vae", "train_step_vqgan",
                  "train_step_clip"):
        gpath = os.path.join(repo, "contracts", f"{entry}.json")
        try:
            with open(gpath) as fh:
                golden = json.load(fh)
            ok = golden.get("transfers") == []
        except OSError:
            ok = False
        check(ok, f"graftir golden {entry}: zero host-transfer primitives "
                  "with health taps pinned")

    # LIVE probe: trace+compile the dVAE train step with the taps on and
    # off, on a real 2x2 dp/fsdp mesh, and diff the contracts directly —
    # the taps must (a) introduce zero host-transfer primitives, (b) keep
    # donation fully aliased, and (c) change the collective inventory by at
    # most scalar-sized all-reduces on axes the step already used (the
    # unavoidable cross-shard combine for group norms of sharded state;
    # no new collective kinds, no new mesh axes, nothing > 1 KB)
    from collections import Counter

    from dalle_tpu.analysis.contracts import BuiltEntry
    from dalle_tpu.analysis.ir_audit import build_contract
    from dalle_tpu.config import DVAEConfig, PrecisionConfig
    from dalle_tpu.train.trainer_vae import VAETrainer
    import jax.numpy as jnp
    probe_cfg = DVAEConfig(image_size=16, num_tokens=32, codebook_dim=16,
                           num_layers=2, hidden_dim=8, num_resnet_blocks=0)
    mesh22_cfg = MeshConfig(dp=2, fsdp=2)
    mesh22 = build_mesh(mesh22_cfg)

    def probe_contract(health: bool) -> dict:
        tc2 = TrainConfig(
            batch_size=8, preflight_checkpoint=False,
            checkpoint_dir=os.path.join(args.outdir, "probe_ckpt"),
            mesh=mesh22_cfg, precision=PrecisionConfig(compute="float32"),
            obs=ObsConfig(health=health))
        tr2 = VAETrainer(probe_cfg, tc2, mesh=mesh22)
        images = tr2._put(rng.rand(8, 16, 16, 3).astype(np.float32),
                          np.float32)
        key = jax.random.fold_in(tr2.base_key, 0)
        donated = len(jax.tree.leaves(tr2.state))
        be = BuiltEntry(fn=tr2.step_fn,
                        args=(tr2.state, images, key, jnp.float32(1.0)),
                        donated=donated, mesh=tr2.mesh, compile=True)
        return build_contract("health_probe", be)

    con_on, con_off = probe_contract(True), probe_contract(False)
    check(con_on["transfers"] == [] and con_off["transfers"] == [],
          "live probe: health taps add no host-transfer primitives")
    don = con_on.get("donation") or {}
    check(don.get("aliased") == don.get("donated"),
          f"live probe: donation fully aliased with taps on "
          f"({don.get('aliased')}/{don.get('donated')})")

    def _series(con):
        return Counter({(c["kind"], c["axes"], c["bytes"]): c["count"]
                        for c in con.get("collectives", [])})

    on_c, off_c = _series(con_on), _series(con_off)
    removed = off_c - on_c
    added = on_c - off_c
    axes_off = {k[1] for k in off_c}
    added_ok = all(kind == "all-reduce" and axes in axes_off
                   and nbytes <= 1024
                   for (kind, axes, nbytes) in added)
    check(not removed and added_ok,
          "live probe: tap delta is scalar all-reduces only, on existing "
          f"axes (added={sorted(added)!r})")

    # -- 7. injected codebook collapse → one bundle + DEGRADED verdict -----
    health_dir = os.path.join(args.outdir, "health_artifacts")
    os.makedirs(health_dir, exist_ok=True)
    obs.configure_recorder(health_dir)
    vae_cfg = DVAEConfig(image_size=16, num_tokens=32, codebook_dim=16,
                         num_layers=2, hidden_dim=8, num_resnet_blocks=0)
    vae_tc = TrainConfig(
        batch_size=4, log_every=1, metrics_every=1, save_every_steps=0,
        preflight_checkpoint=False, device_prefetch=0,
        checkpoint_dir=os.path.join(args.outdir, "vae_ckpt"), mesh=mesh_cfg,
        # the injection: a floor no 32-code codebook can satisfy —
        # perplexity is ≤ num_tokens, so the detector MUST trip (once:
        # edge-triggered, the collapse "persists" every later step). The
        # loss/grad detectors are parked at unreachable thresholds so this
        # 6-step toy run (whose warm-up loss swings would look like spikes
        # to a 2-sample EMA) exercises exactly one detector
        obs=ObsConfig(health=True, health_perplexity_floor=1e6,
                      health_loss_z=1e9, health_grad_factor=1e9,
                      health_min_samples=2))
    vae_tr = VAETrainer(vae_cfg, vae_tc, mesh=build_mesh(
        mesh_cfg, devices=jax.devices()[:1]))
    vae_metrics = os.path.join(health_dir, "vae_metrics.jsonl")
    if os.path.exists(vae_metrics):
        os.remove(vae_metrics)
    vae_writer = MetricsLogger(path=vae_metrics)
    vae_tr.fit(iter([(rng.rand(4, 16, 16, 3).astype(np.float32),)
                     for _ in range(6)]), steps=6,
               metrics_writer=vae_writer, log=lambda *a, **k: None)
    vae_writer.close()
    bundles = [n for n in sorted(os.listdir(health_dir))
               if n.startswith("postmortem_health_codebook-collapse")]
    check(len(bundles) == 1,
          f"injected codebook collapse → exactly one flight bundle "
          f"(got {len(bundles)})")
    if bundles:
        with open(os.path.join(health_dir, bundles[0],
                               "postmortem.json")) as fh:
            pm = json.load(fh)
        breach = (pm.get("extra") or {}).get("breach", {})
        check(breach.get("detector") == "codebook-collapse"
              and breach.get("layer_group") == "codebook",
              f"bundle names detector+group ({breach.get('detector')}, "
              f"{breach.get('layer_group')})")
    vae_report = summarize_run(vae_metrics)
    check("MODEL-HEALTH: DEGRADED (codebook-collapse in codebook" in
          vae_report, "obs_report MODEL-HEALTH: DEGRADED verdict names "
                      "detector and layer group")
    check("=nan" not in vae_report and " nan" not in vae_report,
          "health report free of NaN rates")
    with open(os.path.join(health_dir, "vae_report.txt"), "w") as fh:
        fh.write(vae_report)
    obs.disable_recorder()

    # -- breakdown artifact (uploaded by ci.yml with the trace) ------------
    breakdown = {
        "median_step_s": med_step,
        "median_batch_wait_plus_sync_s": waits[len(waits) // 2] if waits else None,
        "checkpoint_boundary_steps_s": sorted(boundary),
        "records": len(recs), "saves_observed": n_ckpt,
        "health_columns": len(health_cols),
        "health_bundles": bundles,
        "failures": list(FAILURES),
    }
    with open(os.path.join(args.outdir, "breakdown.json"), "w") as fh:
        json.dump(breakdown, fh, indent=2)

    print()
    print(summarize_run(args.outdir))
    obs.disable()
    if FAILURES:
        print(f"\nobs_smoke: FAILED ({len(FAILURES)} checks)")
        return 1
    print("\nobs_smoke: GREEN")
    return 0


if __name__ == "__main__":
    sys.exit(main())
