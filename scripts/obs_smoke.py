#!/usr/bin/env python
"""Observability + host-overlap smoke: a short synthetic traced DALLE fit
with every PR3 overlap layer ON (device prefetch, async checkpointing,
deferred metrics), then assert the telemetry AND overlap contracts end to
end (the CI stage behind docs/OBSERVABILITY.md and docs/PERFORMANCE.md):

  1. the Chrome trace JSON is well-formed, contains fit/batch_wait,
     fit/dispatch and fit/sync spans, and the in-band sync span NESTS inside
     its step's dispatch window (trainer._finish_step runs inside
     fit/dispatch; on-demand/flush syncs are exempt);
  2. the metrics JSONL carries the per-step breakdown — t_batch_wait_s /
     t_dispatch_s / t_sync_s / t_h2d_s, a data-starvation ratio, the HBM
     gauge, and t_ckpt_s on the records after each save boundary;
  3. OVERLAP: steady-state t_batch_wait_s + t_sync_s is ~0 (prefetch keeps
     batches device-resident; deferred metrics read finished steps), and a
     step crossing a checkpoint boundary stays within a bounded multiple of
     the median step time (async save = snapshot only, not
     snapshot+serialize+write);
  4. the watchdog (armed with a generous deadline) stayed quiet;
  5. measured span overhead extrapolated to a full step's span count is
     < 1% of the median step time.

Artifacts (trace.json, spans.jsonl, metrics.jsonl, breakdown.json, the
obs_report summary) land in --outdir; ci.yml uploads them so every CI run
leaves an openable Perfetto trace + the step-breakdown behind.

Run: JAX_PLATFORMS=cpu python scripts/obs_smoke.py --outdir obs_artifacts
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

FAILURES = []


def check(ok: bool, what: str):
    print(("ok   " if ok else "FAIL ") + what)
    if not ok:
        FAILURES.append(what)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--outdir", default="./obs_smoke_out")
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--save_every", type=int, default=5)
    args = ap.parse_args(argv)
    os.makedirs(args.outdir, exist_ok=True)

    import jax
    import numpy as np
    from dalle_tpu import obs
    from dalle_tpu.config import (DalleConfig, MeshConfig, ObsConfig,
                                  TrainConfig)
    from dalle_tpu.obs.report import span_overhead_s, summarize_run
    from dalle_tpu.parallel.mesh import build_mesh
    from dalle_tpu.train.metrics import MetricsLogger
    from dalle_tpu.train.trainer_dalle import DalleTrainer

    tiny = DalleConfig(num_text_tokens=32, text_seq_len=8, dim=32, depth=2,
                       heads=2, dim_head=16, image_size=16,
                       image_vocab_size=32, image_fmap_size=4)
    mesh_cfg = MeshConfig()
    tc = TrainConfig(
        batch_size=4, log_every=1, metrics_every=1,
        save_every_steps=args.save_every, keep_n_checkpoints=2,
        preflight_checkpoint=False,
        async_checkpointing=True, device_prefetch=2, defer_metrics=True,
        rollback_snapshot="auto",
        checkpoint_dir=os.path.join(args.outdir, "ckpt"),
        mesh=mesh_cfg,
        obs=ObsConfig(trace=True, trace_dir=args.outdir,
                      watchdog_deadline_s=300.0, device_poll_every=1))
    # one explicit device: an inherited XLA_FLAGS=...device_count=8 would
    # otherwise auto-scale dp to 8 and reject the batch-4 sharding
    trainer = DalleTrainer(tiny, tc, mesh=build_mesh(
        mesh_cfg, devices=jax.devices()[:1]))

    rng = np.random.RandomState(0)
    batches = [(rng.randint(1, tiny.num_text_tokens, (4, tiny.text_seq_len)),
                rng.randint(0, tiny.image_vocab_size, (4, tiny.image_seq_len)))
               for _ in range(args.steps)]
    metrics_path = os.path.join(args.outdir, "metrics.jsonl")
    if os.path.exists(metrics_path):
        os.remove(metrics_path)
    writer = MetricsLogger(path=metrics_path)
    trainer.fit(iter(batches), steps=args.steps, metrics_writer=writer)
    writer.close()

    # -- 1. trace validity + nesting ---------------------------------------
    trace_path = os.path.join(args.outdir, "trace.json")
    with open(trace_path) as fh:
        doc = json.load(fh)
    events = doc.get("traceEvents", [])
    names = {e["name"] for e in events}
    check(len(events) > 0, f"trace.json parses; {len(events)} events")
    for want in ("fit/step", "fit/batch_wait", "fit/dispatch", "fit/sync",
                 "dalle/step", "dalle/shard_batch", "fit/checkpoint",
                 "ckpt/snapshot", "ckpt/snapshot_good", "data/h2d"):
        check(want in names, f"span present: {want}")
    # nesting: every IN-BAND fit/sync must lie inside some fit/dispatch
    # interval (on-demand save-boundary fetches and the defer-flush run in
    # the fit loop itself, outside dispatch — by design)
    dispatch = [(e["ts"], e["ts"] + e["dur"]) for e in events
                if e["name"] == "fit/dispatch"]
    syncs = [(e["ts"], e["ts"] + e["dur"]) for e in events
             if e["name"] == "fit/sync"
             and not (e.get("args") or {}).get("on_demand")
             and not (e.get("args") or {}).get("flush")]
    nested = all(any(lo <= s0 and s1 <= hi + 1 for lo, hi in dispatch)
                 for s0, s1 in syncs)
    check(bool(syncs) and nested, "in-band fit/sync spans nest inside fit/dispatch")

    # -- 2. breakdown metrics in the JSONL ---------------------------------
    with open(metrics_path) as fh:
        recs = [json.loads(ln) for ln in fh if ln.strip()]
    check(len(recs) >= args.steps - 1,
          f"metrics.jsonl has {len(recs)} records (≥ steps-1)")
    full = [r for r in recs if "data_starvation" in r]
    check(bool(full), "records with the windowed breakdown exist")
    last = full[-1] if full else {}
    for col in ("t_batch_wait_s", "t_dispatch_s", "t_sync_s", "t_h2d_s",
                "data_starvation", "hbm_bytes_in_use", "compiles_total"):
        check(any(col in r for r in recs), f"metric column present: {col}")
    check(0.0 <= last.get("data_starvation", -1) <= 1.0,
          f"data_starvation in [0,1] (last={last.get('data_starvation')})")
    n_ckpt = sum(1 for r in recs if r.get("t_ckpt_s"))
    check(n_ckpt >= 1, f"t_ckpt_s recorded after save boundaries ({n_ckpt})")

    # -- 3. overlap: steady-state stalls ~0; ckpt-boundary step bounded ----
    # per-step walls from fit/step spans, keyed by their step arg; the first
    # two steps carry XLA compiles and are excluded from the steady state
    step_spans = {int(e["args"]["step"]): e["dur"] / 1e6 for e in events
                  if e["name"] == "fit/step" and (e.get("args") or {}).get("step") is not None}
    ckpt_steps = {int(e["args"]["step"]) - 1 for e in events
                  if e["name"] == "fit/checkpoint"}   # span step arg is post-increment
    steady = sorted(dur for s, dur in step_spans.items()
                    if s >= 2 and s not in ckpt_steps)
    boundary = [dur for s, dur in step_spans.items()
                if s >= 2 and s in ckpt_steps]
    med_step = steady[len(steady) // 2] if steady else float("nan")
    waits = sorted(r["t_batch_wait_s"] + r["t_sync_s"] for r in recs
                   if "t_batch_wait_s" in r and not r.get("t_ckpt_s"))
    if waits:
        med_wait = waits[len(waits) // 2]
        # "≈ 0": an in-memory iterator + device-resident batches + deferred
        # sync leave only bookkeeping — bounded by 10% of a (tiny, ~ms-scale)
        # step with a 5 ms absolute floor for CI scheduler noise
        bound = max(0.10 * med_step, 0.005)
        check(med_wait < bound,
              f"steady-state batch_wait+sync ≈ 0 (median {med_wait * 1e3:.3f}ms"
              f" < {bound * 1e3:.2f}ms)")
    else:
        check(False, "no steady-state wait/sync records")
    if boundary and steady:
        worst = max(boundary)
        # async save pays one snapshot, not snapshot+serialize+write: the
        # boundary step must stay within ~2× the median step. The 1 s
        # absolute floor covers the toy regime this smoke runs in: orbax's
        # fixed host dispatch cost (~0.2-0.7 s, amplified on a 1-core CI box
        # where the background writer shares the core) dwarfs a ~20 ms toy
        # step but vanishes next to a real model's step — there the 2× term
        # is the binding constraint
        bound = max(2.0 * med_step, med_step + 1.0)
        check(worst <= bound,
              f"checkpoint-boundary step bounded ({worst * 1e3:.1f}ms ≤ "
              f"{bound * 1e3:.1f}ms; median step {med_step * 1e3:.1f}ms)")
    else:
        check(False, "no checkpoint-boundary step spans found")

    # -- 4. watchdog quiet -------------------------------------------------
    wd = trainer.last_watchdog
    check(wd is not None and wd.stall_count == 0,
          f"watchdog quiet (stalls={getattr(wd, 'stall_count', '?')})")

    # -- 5. span overhead < 1% of step time --------------------------------
    per_span = span_overhead_s()
    spans_per_step = len(events) / max(args.steps, 1)
    dispatch_times = sorted(r["t_dispatch_s"] for r in recs
                            if "t_dispatch_s" in r)
    if dispatch_times:
        med_disp = dispatch_times[len(dispatch_times) // 2]
        overhead = per_span * spans_per_step
        check(overhead < 0.01 * med_disp,
              f"span overhead {overhead * 1e6:.1f}µs ({spans_per_step:.0f} "
              f"spans/step × {per_span * 1e9:.0f}ns) < 1% of median step "
              f"{med_disp * 1e3:.2f}ms")
    else:
        check(False, "no t_dispatch_s records — overhead gate unmeasurable")

    # -- breakdown artifact (uploaded by ci.yml with the trace) ------------
    breakdown = {
        "median_step_s": med_step,
        "median_batch_wait_plus_sync_s": waits[len(waits) // 2] if waits else None,
        "checkpoint_boundary_steps_s": sorted(boundary),
        "records": len(recs), "saves_observed": n_ckpt,
        "failures": list(FAILURES),
    }
    with open(os.path.join(args.outdir, "breakdown.json"), "w") as fh:
        json.dump(breakdown, fh, indent=2)

    print()
    print(summarize_run(args.outdir))
    obs.disable()
    if FAILURES:
        print(f"\nobs_smoke: FAILED ({len(FAILURES)} checks)")
        return 1
    print("\nobs_smoke: GREEN")
    return 0


if __name__ == "__main__":
    sys.exit(main())
