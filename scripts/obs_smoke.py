#!/usr/bin/env python
"""Observability smoke: a 5-step synthetic traced DALLE fit, then assert the
telemetry contract end to end (the CI stage behind docs/OBSERVABILITY.md):

  1. the Chrome trace JSON is well-formed, contains fit/batch_wait,
     fit/dispatch and fit/sync spans, and the sync span NESTS inside its
     step's dispatch window (trainer._finish_step runs inside fit/dispatch);
  2. the metrics JSONL carries the per-step breakdown — t_batch_wait_s /
     t_dispatch_s / t_sync_s, a data-starvation ratio, and the HBM gauge;
  3. the watchdog (armed with a generous deadline) stayed quiet;
  4. measured span overhead extrapolated to a full step's span count is
     < 1% of the median step time.

Artifacts (trace.json, spans.jsonl, metrics.jsonl, the obs_report summary)
land in --outdir; ci.yml uploads them so every CI run leaves an openable
Perfetto trace behind.

Run: JAX_PLATFORMS=cpu python scripts/obs_smoke.py --outdir obs_artifacts
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

FAILURES = []


def check(ok: bool, what: str):
    print(("ok   " if ok else "FAIL ") + what)
    if not ok:
        FAILURES.append(what)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--outdir", default="./obs_smoke_out")
    ap.add_argument("--steps", type=int, default=5)
    args = ap.parse_args(argv)
    os.makedirs(args.outdir, exist_ok=True)

    import jax
    import numpy as np
    from dalle_tpu import obs
    from dalle_tpu.config import (DalleConfig, MeshConfig, ObsConfig,
                                  TrainConfig)
    from dalle_tpu.obs.report import span_overhead_s, summarize_run
    from dalle_tpu.parallel.mesh import build_mesh
    from dalle_tpu.train.metrics import MetricsLogger
    from dalle_tpu.train.trainer_dalle import DalleTrainer

    tiny = DalleConfig(num_text_tokens=32, text_seq_len=8, dim=32, depth=2,
                       heads=2, dim_head=16, image_size=16,
                       image_vocab_size=32, image_fmap_size=4)
    mesh_cfg = MeshConfig()
    tc = TrainConfig(
        batch_size=4, log_every=1, metrics_every=1, save_every_steps=0,
        preflight_checkpoint=False,
        checkpoint_dir=os.path.join(args.outdir, "ckpt"),
        mesh=mesh_cfg,
        obs=ObsConfig(trace=True, trace_dir=args.outdir,
                      watchdog_deadline_s=300.0, device_poll_every=1))
    # one explicit device: an inherited XLA_FLAGS=...device_count=8 would
    # otherwise auto-scale dp to 8 and reject the batch-4 sharding
    trainer = DalleTrainer(tiny, tc, mesh=build_mesh(
        mesh_cfg, devices=jax.devices()[:1]))

    rng = np.random.RandomState(0)
    batches = [(rng.randint(1, tiny.num_text_tokens, (4, tiny.text_seq_len)),
                rng.randint(0, tiny.image_vocab_size, (4, tiny.image_seq_len)))
               for _ in range(args.steps)]
    metrics_path = os.path.join(args.outdir, "metrics.jsonl")
    if os.path.exists(metrics_path):
        os.remove(metrics_path)
    writer = MetricsLogger(path=metrics_path)
    trainer.fit(iter(batches), steps=args.steps, metrics_writer=writer)
    writer.close()

    # -- 1. trace validity + nesting ---------------------------------------
    trace_path = os.path.join(args.outdir, "trace.json")
    with open(trace_path) as fh:
        doc = json.load(fh)
    events = doc.get("traceEvents", [])
    names = {e["name"] for e in events}
    check(len(events) > 0, f"trace.json parses; {len(events)} events")
    for want in ("fit/step", "fit/batch_wait", "fit/dispatch", "fit/sync",
                 "dalle/step", "dalle/shard_batch"):
        check(want in names, f"span present: {want}")
    # nesting: every fit/sync must lie inside some fit/dispatch interval
    dispatch = [(e["ts"], e["ts"] + e["dur"]) for e in events
                if e["name"] == "fit/dispatch"]
    syncs = [(e["ts"], e["ts"] + e["dur"]) for e in events
             if e["name"] == "fit/sync"]
    nested = all(any(lo <= s0 and s1 <= hi + 1 for lo, hi in dispatch)
                 for s0, s1 in syncs)
    check(bool(syncs) and nested, "fit/sync spans nest inside fit/dispatch")

    # -- 2. breakdown metrics in the JSONL ---------------------------------
    with open(metrics_path) as fh:
        recs = [json.loads(ln) for ln in fh if ln.strip()]
    check(len(recs) >= args.steps, f"metrics.jsonl has {len(recs)} records")
    last = recs[-1]
    for col in ("t_batch_wait_s", "t_dispatch_s", "t_sync_s",
                "data_starvation", "hbm_bytes_in_use", "compiles_total"):
        check(any(col in r for r in recs), f"metric column present: {col}")
    check(0.0 <= last.get("data_starvation", -1) <= 1.0,
          f"data_starvation in [0,1] (last={last.get('data_starvation')})")

    # -- 3. watchdog quiet -------------------------------------------------
    wd = trainer.last_watchdog
    check(wd is not None and wd.stall_count == 0,
          f"watchdog quiet (stalls={getattr(wd, 'stall_count', '?')})")

    # -- 4. span overhead < 1% of step time --------------------------------
    per_span = span_overhead_s()
    spans_per_step = len(events) / max(args.steps, 1)
    dispatch_times = sorted(r["t_dispatch_s"] for r in recs
                            if "t_dispatch_s" in r)
    if dispatch_times:
        med_step = dispatch_times[len(dispatch_times) // 2]
        overhead = per_span * spans_per_step
        check(overhead < 0.01 * med_step,
              f"span overhead {overhead * 1e6:.1f}µs ({spans_per_step:.0f} "
              f"spans/step × {per_span * 1e9:.0f}ns) < 1% of median step "
              f"{med_step * 1e3:.2f}ms")
    else:
        check(False, "no t_dispatch_s records — overhead gate unmeasurable")

    print()
    print(summarize_run(args.outdir))
    obs.disable()
    if FAILURES:
        print(f"\nobs_smoke: FAILED ({len(FAILURES)} checks)")
        return 1
    print("\nobs_smoke: GREEN")
    return 0


if __name__ == "__main__":
    sys.exit(main())
