#!/usr/bin/env python
"""Long-cache decode-kernel tiers (VERDICT r4 #5): single-block vs chunked
vs dense XLA, us/layer-step at long cache lengths.

Two regimes:
  * S=1280, h8 d64 (small-model fmap-32 cache): the single-block kernel
    still fits its VMEM budget — measures whether tail-skipping ever beats
    one big DMA at 10+ blocks (the r4 S=512/4-block measurement said no).
  * S=2560, h14 d128 (flagship-head long cache): the merged block is 17.9MB
    — single-block cannot run; the chunked kernel is the only kernel tier
    and competes with dense XLA.

Timed via the dispatched-scan harness (k=64; grads off) at several lengths
(= tail-skip occupancies). Run on TPU; numbers → NEXT.md.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from _bench_util import timed_scan


def run(b, h, S, d, dtype, lengths, blks=(256, 512)):
    from dalle_tpu.ops.attention import KVCache, cached_attend
    from dalle_tpu.ops.decode_attention import (
        decode_attend_kernel, decode_attend_kernel_chunked,
        decode_kernel_supported)

    rng = np.random.RandomState(0)
    c = KVCache.init(b, h, S, d, dtype)
    k = jnp.asarray(rng.standard_normal((b, h, S, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, h, S, d)), jnp.float32)
    cache = c.append(k, v, 0)
    q = jnp.asarray(rng.standard_normal((b, h, 1, d)), jnp.bfloat16)
    single_ok = decode_kernel_supported(q, cache, stable=False)

    for length in lengths:
        ln = jnp.int32(length)
        rows = {"shape": f"b{b}_h{h}_S{S}_d{d}_{jnp.dtype(dtype).name}",
                "length": length}
        # cache rides as an ARGUMENT (a closure would bake the whole buffer
        # into the program proto — the tunnel rejects >100MB compile bodies)
        rows["dense_us"] = round(timed_scan(
            lambda qq, cc: cached_attend(qq, cc, ln, use_kernel=False),
            (q, cache), k=64) * 1e6, 1)
        if single_ok:
            rows["single_us"] = round(timed_scan(
                lambda qq, cc: decode_attend_kernel(qq, cc, ln),
                (q, cache), k=64) * 1e6, 1)
        for blk in blks:
            if S % blk:
                continue
            rows[f"chunk{blk}_us"] = round(timed_scan(
                lambda qq, cc, bb=blk: decode_attend_kernel_chunked(
                    qq, cc, ln, blk=bb),
                (q, cache), k=64) * 1e6, 1)
        print(json.dumps(rows), flush=True)


def main():
    # small-model long cache: single-block still fits
    run(64, 8, 1280, 64, jnp.bfloat16, lengths=(320, 640, 1280))
    run(64, 8, 1280, 64, jnp.int8, lengths=(320, 640, 1280))
    # flagship-head long cache: single-block busts its budget
    run(16, 14, 2560, 128, jnp.bfloat16, lengths=(640, 1280, 2560))
    run(16, 14, 2560, 128, jnp.int8, lengths=(640, 1280, 2560))


if __name__ == "__main__":
    main()
